"""Tokenize raw text into training shards (the missing first mile).

The reference trains from pre-tokenized GCS tar shards whose preparation
scripts live outside the repo (its ``data/index/*.index`` files just point at
finished ``gs://…/*.tar.gz`` artifacts, reference ``main_zero.py:197-198``).
This CLI closes that gap in-tree: raw text in, training-ready data out, in
either of the formats the loaders consume:

- ``memmap``: one flat binary token stream (``uint16``/``uint32``), read by
  ``sources.MemmapSource`` as ``[n_rows, max_context]``;
- ``tar``: ``.tar.gz`` shards of ``.npy`` int32 rows (+ an ``.index`` file
  listing them), read by ``tarshards.TarShardSource``.

Documents are concatenated with a separator token between them and chunked
into fixed ``max_context`` rows — exactly the layout the packed-sequence
trainer expects (``ModelConfig.doc_sep_token`` derives attention masks and
loss boundaries from that separator in-graph). The trailing partial row is
dropped (a partial row would train on garbage padding).

Usage:
  python -m zero_transformer_tpu.data.prepare \\
      --input corpus/*.txt --tokenizer bytes --max-context 2048 \\
      --format tar --rows-per-shard 1024 --out data/corpus

``--tokenizer`` is ``bytes`` (built-in byte-level, vocab 256, zero
downloads) or a HuggingFace name/path (e.g. ``EleutherAI/gpt-neox-20b``,
what the reference trained with). ``--input`` accepts ``.txt`` (one document
per file) and ``.jsonl`` (one document per line under a ``"text"`` key).
"""
from __future__ import annotations

import argparse
import glob
import io
import json
import sys
import tarfile
from pathlib import Path
from typing import Iterable, Iterator, List, Optional

import numpy as np


def iter_documents(inputs: List[str]) -> Iterator[str]:
    """Yield documents from .txt (whole file) / .jsonl ("text" per line)."""
    paths: List[str] = []
    for pattern in inputs:
        hits = sorted(glob.glob(pattern))
        if not hits:
            raise FileNotFoundError(f"no input matches {pattern!r}")
        paths.extend(hits)
    for p in paths:
        if p.endswith(".jsonl"):
            with open(p, encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    doc = json.loads(line)
                    text = doc["text"] if isinstance(doc, dict) else str(doc)
                    if text:
                        yield text
        else:
            text = Path(p).read_text(encoding="utf-8")
            if text:
                yield text


def load_tokenizer(name: str):
    """Same dispatch as the serve CLI ("bytes" builtin, else HuggingFace)."""
    from zero_transformer_tpu.serve import _load_tokenizer

    return _load_tokenizer(name)


def _encode(tokenizer, doc: str) -> List[int]:
    """Tokenize WITHOUT auto-inserted specials: HF tokenizers that prepend
    BOS / append EOS (e.g. Llama) would inject stray tokens before every
    document, corrupting the separator-derived attention/loss boundaries."""
    try:
        return tokenizer.encode(doc, add_special_tokens=False)
    except TypeError:  # builtin/byte tokenizers take no such kwarg
        return tokenizer.encode(doc)


def pack_rows(
    docs: Iterable[str],
    tokenizer,
    max_context: int,
    doc_sep_token: Optional[int],
) -> Iterator[np.ndarray]:
    """Concatenate tokenized docs (separator between them) into fixed rows.

    Streaming: holds at most one row + one document of tokens. The final
    partial row is dropped."""
    buf: List[int] = []
    first = True
    for doc in docs:
        ids = _encode(tokenizer, doc)
        # keyed on "not the first document", NOT on a non-empty buffer — a
        # document that fills rows exactly leaves the buffer empty and must
        # still be separated from the next one
        if doc_sep_token is not None and not first:
            buf.append(doc_sep_token)
        first = False
        buf.extend(ids)
        # emit full rows by index, then truncate ONCE — re-slicing the list
        # per row would be quadratic in document size (one big .txt file is
        # a single document)
        n_full = len(buf) // max_context
        for r in range(n_full):
            yield np.asarray(buf[r * max_context : (r + 1) * max_context], np.int32)
        if n_full:
            del buf[: n_full * max_context]


def write_memmap(rows: Iterator[np.ndarray], out: Path, dtype: str) -> int:
    """Append rows to one flat binary stream; returns rows written."""
    np_dtype = np.dtype(dtype)
    info = np.iinfo(np_dtype)
    n = 0
    with open(out, "wb") as f:
        for row in rows:
            # two-sided: a negative id would silently WRAP under astype
            # (int32 -1 -> uint16 65535 — out-of-vocab garbage at every
            # boundary), not error
            if row.min(initial=0) < info.min or row.max(initial=0) > info.max:
                raise ValueError(
                    f"token ids [{int(row.min())}, {int(row.max())}] out of "
                    f"range for {dtype}; use --dtype uint32 or fix --doc-sep"
                )
            f.write(row.astype(np_dtype).tobytes())
            n += 1
    return n


def write_tar_shards(
    rows: Iterator[np.ndarray], out_prefix: Path, rows_per_shard: int
) -> List[Path]:
    """Write .tar.gz shards of .npy rows plus an .index file."""
    shards: List[Path] = []
    tar: Optional[tarfile.TarFile] = None
    in_shard = 0
    try:
        for i, row in enumerate(rows):
            if tar is None:
                shard_path = Path(f"{out_prefix}-{len(shards):05d}.tar.gz")
                tar = tarfile.open(shard_path, "w:gz")
                shards.append(shard_path)
                in_shard = 0
            payload = io.BytesIO()
            np.save(payload, row)
            data = payload.getvalue()
            info = tarfile.TarInfo(name=f"{i:09d}.input_id.npy")
            info.size = len(data)
            tar.addfile(info, io.BytesIO(data))
            in_shard += 1
            if in_shard >= rows_per_shard:
                tar.close()
                tar = None
    finally:
        if tar is not None:
            tar.close()
    index = Path(f"{out_prefix}.index")
    # entries are shard FILENAMES: read_index resolves relative entries
    # against the index's own directory, so the dataset directory can be
    # moved/copied wholesale and the index keeps working
    index.write_text("".join(f"{s.name}\n" for s in shards))
    return shards


def main(argv=None) -> None:
    p = argparse.ArgumentParser(
        prog="zero_transformer_tpu.data.prepare", description=__doc__
    )
    p.add_argument("--input", nargs="+", required=True,
                   help=".txt / .jsonl files or globs")
    p.add_argument("--tokenizer", default="bytes",
                   help='"bytes" or a HuggingFace tokenizer name/path')
    p.add_argument("--max-context", type=int, default=2048,
                   help="row length (the reference stored 2048, conf/config.yaml:22)")
    p.add_argument("--format", choices=("memmap", "tar"), default="memmap")
    p.add_argument("--out", required=True,
                   help="output file (memmap) or shard prefix (tar)")
    p.add_argument("--dtype", default="uint16",
                   help="memmap storage dtype (uint16 fits vocab 50304)")
    p.add_argument("--rows-per-shard", type=int, default=1024)
    p.add_argument("--doc-sep", type=int, default=None,
                   help="separator token id between documents (enables the "
                        "packed-sequence workflow; match model.doc_sep_token). "
                        "Default: the tokenizer's EOS if it has one, else none")
    args = p.parse_args(argv)

    tokenizer = load_tokenizer(args.tokenizer)
    sep = args.doc_sep
    if sep is None:
        sep = getattr(tokenizer, "eos_token_id", None)
    # validated HERE, once, for both output formats — the tar path stores
    # int32 and would otherwise bake a negative separator into every
    # document boundary (nn.Embed clamps out-of-bounds ids silently under
    # jit, so this would train on wrong embeddings with no error)
    if sep is not None and sep < 0:
        raise ValueError(f"--doc-sep must be a valid token id, got {sep}")
    rows = pack_rows(
        iter_documents(args.input), tokenizer, args.max_context, sep
    )
    out = Path(args.out)
    out.parent.mkdir(parents=True, exist_ok=True)
    if args.format == "memmap":
        n = write_memmap(rows, out, args.dtype)
        print(f"wrote {n} rows x {args.max_context} tokens ({args.dtype}) -> {out}")
    else:
        n = 0

        def counted():
            nonlocal n
            for r in rows:
                n += 1
                yield r

        shards = write_tar_shards(counted(), out, args.rows_per_shard)
        print(
            f"wrote {n} rows x {args.max_context} tokens over "
            f"{len(shards)} shards -> {out}-*.tar.gz (+ {out}.index)"
        )
    if n == 0:
        print(
            "warning: 0 full rows (inputs shorter than --max-context); "
            "nothing to train on",
            file=sys.stderr,
        )


if __name__ == "__main__":
    main()
