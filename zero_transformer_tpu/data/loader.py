"""Batch loader: token rows → sharded [accum, batch, seq] training batches.

Replaces the reference's torch ``DataLoader`` + ``numpy_collate`` + host-side
reshape stack (reference ``main_zero.py:407-421,477-493``, ``src/utils/
dataloader.py:9-16``) with a pure-numpy iterator — no torch import anywhere in
the training path — plus a ``device_put_batch`` that builds a global sharded
``jax.Array`` directly from process-local data (multi-host ready).

Semantics kept from the reference:
- **process striping**: process ``p`` consumes source rows ``p, p+P, p+2P…``
  (reference ``split_by_jax_process``, ``main_zero.py:377-387``);
- **sequence curriculum**: rows stored at ``max_context`` are split into
  ``max_context // train_context`` shorter rows (reference
  ``main_zero.py:425-428,477-478``);
- **resume**: ``skip(n_steps)`` fast-forwards via ``source.seek`` — O(1) for
  in-repo sources vs the reference's O(n) islice discard (``:470-471``).
"""
from __future__ import annotations

import collections
import queue
import threading
from typing import Iterator, Optional

import jax
import numpy as np

from zero_transformer_tpu.data.sources import TokenSource


class DataLoader:
    """Yields [accum_steps, local_batch, train_context] int32 batches.

    Args:
      source: per-process token source (rows of ``max_context`` tokens).
      batch_size: GLOBAL batch in sequences of ``train_context``.
      train_context: training sequence length (≤ source.max_context).
      accum_steps: gradient-accumulation microbatch count.
      process_index/process_count: multi-host striping (defaults to jax).
      shuffle_buffer: streaming shuffle-buffer size (0 = off; MemmapSource
        already permutes rows per epoch, so 0 is right for it).
      seed: shuffle-buffer rng seed.
      prefetch: batches decoded ahead by a background thread (0 = fully
        synchronous). With prefetch > 0, ``next(it)`` overlaps host decode
        (gzip/tar/memmap reads release the GIL) with device compute — the
        role the reference's torch ``DataLoader`` workers played (reference
        ``main_zero.py:407-421``). ``steps_consumed`` counts batches
        *yielded*, never batches merely read ahead, so resume state stays
        exact.
    """

    def __init__(
        self,
        source: TokenSource,
        batch_size: int,
        train_context: Optional[int] = None,
        accum_steps: int = 1,
        process_index: Optional[int] = None,
        process_count: Optional[int] = None,
        shuffle_buffer: int = 0,
        seed: int = 23,
        prefetch: int = 0,
    ):
        self.source = source
        self.batch_size = batch_size
        self.train_context = train_context or source.max_context
        self.accum_steps = accum_steps
        self.process_index = (
            process_index if process_index is not None else jax.process_index()
        )
        self.process_count = (
            process_count if process_count is not None else jax.process_count()
        )
        self.shuffle_buffer = shuffle_buffer
        self.seed = seed
        self.prefetch = prefetch
        self.steps_consumed = 0
        # batches a torn-down prefetching iterator had read ahead but never
        # yielded; the next iterator serves them first so the stream is
        # identical to the synchronous path even across re-iteration
        self._leftover: collections.deque = collections.deque()
        # A source that stripes itself (e.g. TarShardSource shard striping)
        # already yields only this process's rows.
        self.pre_striped = bool(getattr(source, "pre_striped", False))

        if source.max_context % self.train_context:
            raise ValueError(
                f"max_context {source.max_context} not divisible by "
                f"train_context {self.train_context}"
            )
        self.split = source.max_context // self.train_context
        if batch_size % self.process_count:
            raise ValueError(
                f"batch_size {batch_size} not divisible by "
                f"{self.process_count} processes"
            )
        seqs_per_step = batch_size * accum_steps
        if seqs_per_step % (self.split * self.process_count):
            raise ValueError(
                f"batch_size*accum ({seqs_per_step}) must divide by "
                f"split*processes ({self.split * self.process_count})"
            )
        # source rows consumed per step by THIS process
        self.rows_per_step = seqs_per_step // self.split // self.process_count
        self.local_batch = batch_size // self.process_count

    def _striped_rows(self) -> Iterator[np.ndarray]:
        if self.pre_striped:
            yield from iter(self.source)
            return
        for i, row in enumerate(iter(self.source)):
            if i % self.process_count == self.process_index:
                yield row

    def _shuffled_rows(self) -> Iterator[np.ndarray]:
        rows = self._striped_rows()
        if not self.shuffle_buffer:
            yield from rows
            return
        rng = np.random.default_rng(
            np.random.SeedSequence([self.seed, self.process_index])
        )
        buf = []
        for row in rows:
            if len(buf) < self.shuffle_buffer:
                buf.append(row)
                continue
            j = rng.integers(len(buf))
            buf[j], row = row, buf[j]
            yield row
        rng.shuffle(buf)
        yield from buf

    def _batches(self) -> Iterator[np.ndarray]:
        """Synchronous batch assembly (no bookkeeping — ``__iter__`` owns it)."""
        rows = self._shuffled_rows()
        n = self.rows_per_step
        while True:
            block = np.stack([next(rows) for _ in range(n)])  # [n, max_context]
            yield block.reshape(
                self.accum_steps, self.local_batch, self.train_context
            )

    def _prefetched(self) -> Iterator[np.ndarray]:
        """Bounded-queue producer thread running ``_batches`` ahead of the
        consumer. Exceptions (including source exhaustion) are re-raised at
        the consuming ``next`` so error behavior matches the sync path.

        Teardown contract: abandoning this iterator must not lose stream
        position — read-ahead the consumer never saw is parked in
        ``self._leftover`` for the next iterator (the producer advanced the
        source past those batches)."""
        q: queue.Queue = queue.Queue(maxsize=self.prefetch)
        stop = threading.Event()
        held: list = []  # batch produced but never queued before teardown
        DONE, ERROR = object(), object()

        def put_polling(item) -> bool:
            """Blocking put that still honors ``stop`` (a plain q.put could
            block forever once the consumer is gone and the queue full)."""
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.2)
                    return True
                except queue.Full:
                    continue
            return False

        def producer():
            try:
                for batch in self._batches():
                    if not put_polling(batch):
                        held.append(batch)
                        return
                put_polling(DONE)
            except BaseException as e:  # forward to consumer
                put_polling((ERROR, e))

        thread = threading.Thread(
            target=producer, daemon=True, name="zt-data-prefetch"
        )
        thread.start()
        try:
            while True:
                item = q.get()
                if item is DONE:
                    return
                if isinstance(item, tuple) and item and item[0] is ERROR:
                    raise item[1]
                yield item
        finally:
            stop.set()
            thread.join()
            while True:  # park unseen read-ahead for the next iterator
                try:
                    item = q.get_nowait()
                except queue.Empty:
                    break
                if item is DONE or (
                    isinstance(item, tuple) and item and item[0] is ERROR
                ):
                    continue
                self._leftover.append(item)
            self._leftover.extend(held)

    def __iter__(self) -> Iterator[np.ndarray]:
        # read-ahead parked by a previous (abandoned) prefetching iterator
        # comes first: those batches precede the source's current position
        while self._leftover:
            self.steps_consumed += 1
            yield self._leftover.popleft()
        batches = self._prefetched() if self.prefetch > 0 else self._batches()
        for batch in batches:
            self.steps_consumed += 1
            yield batch

    def skip(self, n_steps: int) -> None:
        """Fast-forward past ``n_steps`` batches (resume). Seeks the source in
        GLOBAL rows so striping stays aligned across processes; a pre-striped
        source counts positions in its own (local) rows instead."""
        # parked read-ahead is already past the source position: discard it
        # from the front before seeking the remainder
        take = min(n_steps, len(self._leftover))
        for _ in range(take):
            self._leftover.popleft()
        n = (n_steps - take) * self.rows_per_step
        self.source.seek(n if self.pre_striped else n * self.process_count)
        self.steps_consumed += n_steps

    def fault_counters(self) -> dict:
        """Data-path fault accounting from the source (shard retries, skipped
        shards/members — ``TarShardSource.fault_counters``), reported by the
        Trainer through ``MetricsLogger`` at log points. Sources without
        fault accounting contribute nothing."""
        counters = getattr(self.source, "fault_counters", None)
        return dict(counters) if isinstance(counters, dict) else {}

    def state(self) -> dict:
        """Resume token. Only the step count: per-process source positions
        diverge mid-stripe (the striped generator reads ahead to find its
        rows), so the only state that is identical across processes — and
        therefore safe to broadcast from the checkpoint — is how many steps
        were consumed. ``restore`` re-derives the exact per-process position
        from it."""
        return {"steps_consumed": self.steps_consumed}

    def restore(self, state: dict) -> None:
        if self.steps_consumed:
            raise RuntimeError(
                "DataLoader.restore requires a freshly-constructed loader "
                f"(already consumed {self.steps_consumed} steps)"
            )
        self.skip(int(state["steps_consumed"]))


def device_put_batch(local_batch: np.ndarray, sharding) -> jax.Array:
    """Build the global sharded jax.Array from this process's slice.

    ``local_batch`` is [accum, local_batch, seq]; the result is the global
    [accum, global_batch, seq] array laid out per ``sharding`` (batch dim over
    the data axis, seq over the sequence axis). Works single- and multi-host —
    the multi-host replacement for the reference's implicit per-device xmap
    batch splitting (``main_zero.py:477-493``).
    """
    return jax.make_array_from_process_local_data(sharding, local_batch)
