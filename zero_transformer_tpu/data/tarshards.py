"""Tar-shard streaming source (the reference's webdataset pipeline, in-tree).

The reference streams ``.tar.gz`` shards whose names come from index files of
brace-expanded URL patterns (reference ``data/index/*.index``, e.g.
``gs://bucket/pile_train-{000000..000928}.tar.gz``; pipeline at
``main_zero.py:389-405``: shard list → per-process split → tar → decode →
truncate to max_context). This module reproduces that capability with the
standard library:

- ``expand_braces`` — webdataset-style ``{000..012}`` numeric range expansion;
- ``read_index`` — index file → shard path list (``#`` comments skipped);
- ``TarShardSource`` — iterates samples out of (optionally gzipped) tar
  shards; shard order reshuffles each epoch from (seed, epoch) so every
  process derives the same order with no communication. With many shards it
  stripes at SHARD granularity per process (each host opens only its own
  shards — the reference's per-process shard split, ``main_zero.py:389-405``)
  and flags itself ``pre_striped`` so the loader skips row striping; with few
  shards it falls back to every-process-reads-everything + loader row
  striping.

Sample decoding: each tar member is one sample; supported payloads are
``.npy`` (numpy), ``.json`` (list of ints), ``.bin``/``.u16`` (raw uint16),
and ``.pth``/``.pt`` (torch.load, import-gated) — the reference's samples are
``input_id.pth`` tensors (``main_zero.py:368-373``). Rows shorter than
``max_context`` are skipped, longer ones truncated (reference preprocess
semantics). Remote (``gs://``…) paths are opened through ``fsspec`` when it
is importable; plain local paths need nothing.
"""
from __future__ import annotations

import gzip
import io
import json
import logging
import os
import re
import tarfile
import time
from pathlib import Path
from typing import Iterator, List, Sequence

import numpy as np

from zero_transformer_tpu.data.sources import ReplayStreamSource

log = logging.getLogger("zero_transformer_tpu")

_BRACE = re.compile(r"\{(\d+)\.\.(\d+)\}")


def expand_braces(pattern: str) -> List[str]:
    """``a-{000..002}.tar`` → [a-000.tar, a-001.tar, a-002.tar] (recursive)."""
    m = _BRACE.search(pattern)
    if not m:
        return [pattern]
    lo, hi = m.group(1), m.group(2)
    width = len(lo)
    out: List[str] = []
    for i in range(int(lo), int(hi) + 1):
        head = pattern[: m.start()] + str(i).zfill(width) + pattern[m.end() :]
        out.extend(expand_braces(head))
    return out


def read_index(
    path: str | Path, legacy_cwd_fallback: bool | None = None
) -> List[str]:
    """Index file → expanded shard list (reference ``data/index/*.index``).

    Relative local entries resolve against the index file's OWN directory —
    an index written next to its shards keeps working after the dataset
    directory is moved/copied, and is independent of the training job's
    cwd. Absolute paths and remote URLs (``gs://…``) pass through verbatim.

    Compat: before round 3 relative entries resolved against the process
    cwd. That fallback is OPT-IN (``legacy_cwd_fallback=True`` or env
    ``ZT_INDEX_CWD_FALLBACK=1``): a partially-copied dataset plus a
    same-layout dataset in the cwd must fail loudly by default, not train
    on the wrong shards behind a warning that scrolls away (the non-strict
    tar source would otherwise skip the missing shards at open time and
    quietly reshape the stream). Without the opt-in, an entry that exists
    only cwd-relative raises with the remedy in the message.
    """
    if legacy_cwd_fallback is None:
        legacy_cwd_fallback = os.environ.get("ZT_INDEX_CWD_FALLBACK") == "1"
    base = Path(path).parent
    shards: List[str] = []
    for line in Path(path).read_text().splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        for s in expand_braces(line):
            if "://" not in s and not Path(s).is_absolute():
                resolved = base / s
                if not resolved.exists() and Path(s).exists():
                    if not legacy_cwd_fallback:
                        raise ValueError(
                            f"index entry {s!r} missing at {resolved} but "
                            f"present cwd-relative at {Path(s).resolve()} — "
                            "refusing to guess which dataset you meant. "
                            "Move/complete the dataset next to the index, "
                            "or opt in to the legacy cwd resolution with "
                            "legacy_cwd_fallback=True (TarShardSource / "
                            "read_index) or ZT_INDEX_CWD_FALLBACK=1"
                        )
                    log.warning(
                        "index entry %r missing at %s; using the legacy "
                        "cwd-relative path %s (ZT_INDEX_CWD_FALLBACK)",
                        s, resolved, Path(s).resolve(),
                    )
                    resolved = Path(s)  # legacy cwd-relative index entry
                s = str(resolved)
            shards.append(s)
    if not shards:
        raise ValueError(f"index {path} lists no shards")
    return shards


def _open_shard(path: str):
    if "://" in path:
        import fsspec  # gated: remote filesystems only

        raw = fsspec.open(path, "rb").open()
    else:
        raw = open(path, "rb")
    if path.endswith((".gz", ".tgz")):
        return gzip.open(raw)
    return raw


def _decode_member(name: str, data: bytes):
    if name.endswith(".npy"):
        return np.load(io.BytesIO(data), allow_pickle=False)
    if name.endswith(".json"):
        return np.asarray(json.loads(data.decode()))
    if name.endswith((".bin", ".u16")):
        return np.frombuffer(data, dtype=np.uint16)
    if name.endswith((".pth", ".pt")):
        import torch  # gated: only for reference-format shards

        return np.asarray(torch.load(io.BytesIO(data), weights_only=True))
    return None  # unknown payload (e.g. __key__ metadata): skip


class TarShardSource(ReplayStreamSource):
    """Stream token rows out of tar shards, webdataset-style.

    Args:
      shards: shard paths/patterns, OR a single ``*.index`` file path.
      max_context: row length (shorter samples skipped, longer truncated).
      seed: shard-order shuffle seed; order reshuffles each epoch.
      shuffle_shards: False keeps index order (validation).
      process_index/process_count: multi-host placement for shard striping.
      stripe_shards: "auto" stripes at shard granularity when every process
        can own >= 2 shards (per-host IO then scales 1/P instead of every
        host decompressing every shard); True forces it, False disables.
      legacy_cwd_fallback: resolve index entries that only exist relative to
        the process cwd (pre-round-3 index layout) instead of raising; None
        (default) reads the ZT_INDEX_CWD_FALLBACK env var. See
        ``read_index``.
      strict: False (default) logs and skips undecodable members / unreadable
        shards instead of crashing a multi-day run on one bad byte — the
        reference's ``wds.warn_and_continue`` semantics (reference
        ``main_zero.py:392-394``); shard-open failures get one retry so a
        transient remote-IO blip doesn't edit the stream. True re-raises
        immediately (tests, data validation). CAVEAT: skipping is only
        DETERMINISTIC for persistent corruption; if flaky remote IO skips a
        shard on one host (or on the original pass but not a resume replay),
        row striping / resume positions shift — prefer strict=True when the
        storage layer is suspect.

    Resume: ``seek``/``restore`` replay the stream and discard
    (``ReplayStreamSource``) — the reference's islice fast-forward
    (``main_zero.py:470-471``); O(rows) but exact for any shard contents.
    Positions are counted in the rows THIS process yields, striped or not.
    """

    def __init__(
        self,
        shards: str | Sequence[str],
        max_context: int,
        seed: int = 23,
        shuffle_shards: bool = True,
        process_index: int = 0,
        process_count: int = 1,
        stripe_shards: bool | str = "auto",
        strict: bool = False,
        legacy_cwd_fallback: bool | None = None,
        retry_backoff_s: float = 1.0,
    ):
        if isinstance(shards, (str, Path)):
            shards = [str(shards)]
        expanded: List[str] = []
        for s in shards:
            s = str(s)
            if s.endswith(".index"):
                expanded.extend(read_index(s, legacy_cwd_fallback))
            else:
                expanded.extend(expand_braces(s))
        if not expanded:
            raise ValueError("no shards")
        self.shards = expanded
        self.max_context = max_context
        self.seed = seed
        self.shuffle_shards = shuffle_shards
        self.process_index = process_index
        self.process_count = process_count
        if stripe_shards == "auto":
            stripe_shards = len(expanded) >= 2 * process_count
        elif stripe_shards and len(expanded) < process_count:
            raise ValueError(
                f"stripe_shards=True with {len(expanded)} shards < "
                f"{process_count} processes: some processes would own no "
                "shards and yield nothing"
            )
        # pre_striped tells the DataLoader this source already yields only
        # this process's rows, so its row striping must be skipped.
        self.pre_striped = bool(stripe_shards) and process_count > 1
        self.strict = strict
        self.retry_backoff_s = retry_backoff_s
        # fault accounting, surfaced through DataLoader.fault_counters() into
        # the metrics stream: a multi-day pod run must SHOW what it skipped
        # (silent skips reshape the data distribution invisibly)
        self.fault_counters: dict[str, int] = {
            "shard_retries": 0,
            "skipped_shards": 0,
            "skipped_shard_remainders": 0,
            "skipped_members": 0,
        }
        super().__init__()

    def _shard_order(self, epoch: int) -> List[str]:
        if self.shuffle_shards:
            rng = np.random.default_rng(np.random.SeedSequence([self.seed, epoch]))
            order = [self.shards[i] for i in rng.permutation(len(self.shards))]
        else:
            order = list(self.shards)
        if self.pre_striped:
            # every process computes the same global order, then takes its
            # disjoint slice — reshuffled each epoch so ownership rotates
            order = order[self.process_index :: self.process_count]
        return order

    def _shard_rows(self, shard: str) -> Iterator[np.ndarray]:
        with _open_shard(shard) as raw, tarfile.open(fileobj=raw, mode="r|") as tar:
            for member in tar:
                if not member.isfile():
                    continue
                try:
                    data = tar.extractfile(member).read()
                    ids = _decode_member(member.name, data)
                except Exception:
                    if self.strict:
                        raise
                    self.fault_counters["skipped_members"] += 1
                    log.warning(
                        "skipping undecodable member %s in %s",
                        member.name, shard, exc_info=True,
                    )
                    continue
                if ids is None:
                    continue
                ids = np.asarray(ids).reshape(-1)
                if len(ids) < self.max_context:
                    continue
                yield ids[: self.max_context].astype(np.int32)

    def _samples(self) -> Iterator[np.ndarray]:
        epoch = 0
        while True:
            yielded = 0
            for shard in self._shard_order(epoch):
                # retries before skipping: a transient remote-IO blip must
                # not edit the stream (a skipped shard shifts every later
                # row position — see the strict docstring caveat). A shard
                # that fails AFTER yielding rows cannot be retried (the
                # already-yielded prefix would duplicate) — its remainder is
                # skipped.
                for attempt in range(3):
                    from_this_shard = 0
                    try:
                        for row in self._shard_rows(shard):
                            from_this_shard += 1
                            yielded += 1
                            yield row
                        break
                    except Exception:
                        if self.strict:
                            raise
                        if attempt < 2 and from_this_shard == 0:
                            self.fault_counters["shard_retries"] += 1
                            # bounded exponential backoff: a remote-IO blip
                            # (bucket throttle, connection reset) clears in
                            # seconds; an immediate re-open mostly re-fails
                            delay = self.retry_backoff_s * (2.0 ** attempt)
                            log.warning(
                                "retrying shard %s in %.1fs (attempt %d)",
                                shard, delay, attempt + 2,
                            )
                            if delay > 0:
                                time.sleep(delay)
                            continue
                        key = (
                            "skipped_shard_remainders"
                            if from_this_shard
                            else "skipped_shards"
                        )
                        self.fault_counters[key] += 1
                        log.warning(
                            "skipping %s of shard %s",
                            "remainder" if from_this_shard else "all",
                            shard, exc_info=True,
                        )
                        break
            if yielded == 0:
                # every shard failed or filtered to nothing: raising beats a
                # silent infinite busy-loop of warnings
                raise RuntimeError(
                    f"tar source produced zero rows in one full epoch over "
                    f"{len(self.shards)} shard(s) — bad paths, corrupt data, "
                    f"or all rows shorter than max_context={self.max_context}"
                )
            epoch += 1
