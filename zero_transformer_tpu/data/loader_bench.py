"""Loader-throughput microbenchmark for the tar-gzip data path.

Answers two questions the training-step bench can't:
1. raw decode throughput of ``TarShardSource`` (gzip + tar + numpy decode),
   in rows/s and MB/s — the ceiling the data pipeline puts on training;
2. how much of a simulated device step the ``DataLoader``'s background
   prefetch actually hides (sync vs prefetch wall time per step).

The reference overlapped host decode with device compute via torch
DataLoader workers (reference ``main_zero.py:407-421``); here the same
overlap comes from ``DataLoader(prefetch=N)``. Run directly::

    python -m zero_transformer_tpu.data.loader_bench

or via ``bench.py`` (rides in the ``extra.loader_microbench`` field).
"""
from __future__ import annotations

import io
import json
import os
import tarfile
import tempfile
import time

import numpy as np

from zero_transformer_tpu.data.loader import DataLoader
from zero_transformer_tpu.data.tarshards import TarShardSource


def make_shards(
    directory: str,
    n_shards: int = 4,
    rows_per_shard: int = 128,
    max_context: int = 2048,
    seed: int = 0,
) -> list:
    """Write gzipped tar shards of .npy token rows (webdataset layout)."""
    rng = np.random.default_rng(seed)
    paths = []
    for s in range(n_shards):
        path = os.path.join(directory, f"shard-{s:05d}.tar.gz")
        with tarfile.open(path, "w:gz") as tar:
            for r in range(rows_per_shard):
                row = rng.integers(0, 50304, max_context).astype(np.uint16)
                buf = io.BytesIO()
                np.save(buf, row)
                data = buf.getvalue()
                info = tarfile.TarInfo(name=f"{s:05d}-{r:05d}.input_id.npy")
                info.size = len(data)
                tar.addfile(info, io.BytesIO(data))
        paths.append(path)
    return paths


def run(
    n_shards: int = 4,
    rows_per_shard: int = 128,
    max_context: int = 2048,
    batch_rows: int = 8,
    simulated_step_s: float = 0.02,
) -> dict:
    with tempfile.TemporaryDirectory(prefix="zt_loader_bench") as tmp:
        shards = make_shards(tmp, n_shards, rows_per_shard, max_context)
        total_rows = n_shards * rows_per_shard
        n_steps = total_rows // batch_rows - 1  # one epoch, minus warmup slack

        # 1. raw source decode throughput
        src = TarShardSource(shards, max_context=max_context, shuffle_shards=False)
        it = iter(src)
        next(it)  # open/first-decode warmup
        t0 = time.perf_counter()
        for _ in range(total_rows - 1):
            next(it)
        dt = time.perf_counter() - t0
        rows_s = (total_rows - 1) / dt
        mb_s = rows_s * max_context * 2 / 1e6  # uint16 payload bytes

        # 2. overlap: consumer "computes" simulated_step_s per batch
        def consume(prefetch: int) -> float:
            src = TarShardSource(
                shards, max_context=max_context, shuffle_shards=False
            )
            dl = DataLoader(
                src, batch_size=batch_rows, train_context=max_context,
                process_index=0, process_count=1, prefetch=prefetch,
            )
            it = iter(dl)
            next(it)  # warmup: spin up producer / first decode
            t0 = time.perf_counter()
            for _ in range(n_steps):
                next(it)
                time.sleep(simulated_step_s)
            return (time.perf_counter() - t0) / n_steps

        sync_s = consume(0)
        pre_s = consume(2)
        return {
            "decode_rows_per_s": round(rows_s, 1),
            "decode_MB_per_s": round(mb_s, 1),
            "simulated_step_s": simulated_step_s,
            "sync_step_s": round(sync_s, 4),
            "prefetch_step_s": round(pre_s, 4),
            # 1.0 = prefetch hides ALL decode time behind the step; None
            # when sync decode is already ~free (metric would be noise)
            "decode_hidden_frac": (
                round(max(0.0, min(1.0, (sync_s - pre_s) / (sync_s - simulated_step_s))), 3)
                if sync_s - simulated_step_s > 1e-3
                else None
            ),
        }


if __name__ == "__main__":
    print(json.dumps(run()))
