"""Checkpoint export / import / surgery CLI.

Torch-free replacement for the reference's two-step export pipeline
(reference ``torch_compatability/extract_msgpack.py:10-17`` pulls params out
of a TrainState checkpoint into msgpack; ``convert_to_torch.py:13-23`` turns
that into a CUDA-side state dict). Here the interchange format stays flax
msgpack — consumable by anything flax — and depth-extension surgery
(reference ``src/utils/extend_params.py``) is a subcommand instead of a
notebook ritual.

Usage:
  python -m zero_transformer_tpu.export extract  --checkpoint-dir ckpts [--step N] --out params.msgpack
  python -m zero_transformer_tpu.export extend   --params params.msgpack --layers 24 --out big.msgpack
  python -m zero_transformer_tpu.export inspect  --params params.msgpack
"""
from __future__ import annotations

import argparse
from pathlib import Path

import jax
import numpy as np


def _cmd_extract(args) -> None:
    import orbax.checkpoint as ocp

    from zero_transformer_tpu.checkpoint import export_params_msgpack

    directory = Path(args.checkpoint_dir).absolute()
    with ocp.CheckpointManager(directory) as mgr:
        step = args.step if args.step is not None else mgr.latest_step()
        if step is None:
            raise SystemExit(f"no checkpoints under {directory}")
        # structure-agnostic raw read; keep only params
        restored = mgr.restore(step, args=ocp.args.Composite(state=ocp.args.StandardRestore()))
    state = restored["state"]
    params = state["params"] if isinstance(state, dict) else state.params
    out = export_params_msgpack(params, args.out)
    n = sum(int(np.prod(x.shape)) for x in jax.tree.leaves(params))
    print(f"wrote {n:,} params (step {step}) -> {out}")


def _cmd_extend(args) -> None:
    from flax.serialization import msgpack_serialize

    from zero_transformer_tpu.checkpoint import import_params_msgpack
    from zero_transformer_tpu.utils.surgery import extend_depth, num_layers

    params = import_params_msgpack(args.params)
    old = num_layers(params)
    params = extend_depth(params, args.layers)
    Path(args.out).write_bytes(msgpack_serialize(params))
    print(f"extended {old} -> {args.layers} layers -> {args.out}")


def _cmd_upcycle(args) -> None:
    import jax
    import numpy as np
    from flax.serialization import msgpack_serialize

    from zero_transformer_tpu.checkpoint import import_params_msgpack
    from zero_transformer_tpu.utils.surgery import is_stacked, stack_blocks, upcycle_moe

    params = import_params_msgpack(args.params)
    if not is_stacked(params):
        params = stack_blocks(params)
    params = upcycle_moe(params, args.experts)
    Path(args.out).write_bytes(
        msgpack_serialize(jax.tree.map(np.asarray, params))
    )
    print(f"upcycled dense -> {args.experts} experts -> {args.out}")


def _cmd_inspect(args) -> None:
    from zero_transformer_tpu.checkpoint import import_params_msgpack
    from zero_transformer_tpu.utils.surgery import is_stacked, num_layers

    params = import_params_msgpack(args.params)
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    total = 0
    for path, leaf in flat:
        name = "/".join(str(getattr(k, "key", k)) for k in path)
        print(f"{name:60s} {str(leaf.dtype):10s} {tuple(leaf.shape)}")
        total += int(np.prod(leaf.shape))
    print(
        f"-- {total:,} params, {num_layers(params)} layers "
        f"({'stacked' if is_stacked(params) else 'per-block'} layout)"
    )


def main(argv=None) -> None:
    p = argparse.ArgumentParser(prog="zero_transformer_tpu.export", description=__doc__)
    sub = p.add_subparsers(dest="cmd", required=True)

    ex = sub.add_parser("extract", help="orbax checkpoint -> params msgpack")
    ex.add_argument("--checkpoint-dir", required=True)
    ex.add_argument("--step", type=int, default=None)
    ex.add_argument("--out", required=True)
    ex.set_defaults(fn=_cmd_extract)

    et = sub.add_parser("extend", help="depth-extend params (Gopher G.3.3 warm start)")
    et.add_argument("--params", required=True)
    et.add_argument("--layers", type=int, required=True)
    et.add_argument("--out", required=True)
    et.set_defaults(fn=_cmd_extend)

    up = sub.add_parser(
        "upcycle", help="dense params -> MoE warm start (sparse upcycling)"
    )
    up.add_argument("--params", required=True)
    up.add_argument("--experts", type=int, required=True)
    up.add_argument("--out", required=True)
    up.set_defaults(fn=_cmd_upcycle)

    ins = sub.add_parser("inspect", help="list tensors in a params msgpack")
    ins.add_argument("--params", required=True)
    ins.set_defaults(fn=_cmd_inspect)

    args = p.parse_args(argv)
    args.fn(args)


if __name__ == "__main__":
    main()
