"""Checkpoint export / import / surgery CLI.

Torch-free replacement for the reference's two-step export pipeline
(reference ``torch_compatability/extract_msgpack.py:10-17`` pulls params out
of a TrainState checkpoint into msgpack; ``convert_to_torch.py:13-23`` turns
that into a CUDA-side state dict). Here the interchange format stays flax
msgpack — consumable by anything flax — and depth-extension surgery
(reference ``src/utils/extend_params.py``) is a subcommand instead of a
notebook ritual.

Usage:
  python -m zero_transformer_tpu.export extract  --checkpoint-dir ckpts [--step N] --out params.msgpack
  python -m zero_transformer_tpu.export extend   --params params.msgpack --layers 24 --out big.msgpack
  python -m zero_transformer_tpu.export inspect  --params params.msgpack
  python -m zero_transformer_tpu.export import-reference --params ref.msgpack --model 1_3b --out ours.msgpack
  python -m zero_transformer_tpu.export to-reference --params ours.msgpack --model 1_3b --out ref.msgpack
"""
from __future__ import annotations

import argparse
from pathlib import Path

import jax
import numpy as np

# Leaf renaming per reference block (reference ``src/models/GPT.py:16-50``
# auto-names its submodules; ``layers.py`` Dense layers are all
# use_bias=False, LayerNorms scale-only, qkv kernels share our [in, (head,
# head_dim)] channel order, so conversion is a pure rename + per-layer
# stack). Its key-position-only ALiBi bias differs from ours by a per-query
# constant, which softmax cancels — the converted model computes the same
# function.
_REF_BLOCK_MAP = {
    ("LayerNorm_0", "scale"): ("ln_attn", "scale"),
    ("LayerNorm_1", "scale"): ("ln_mlp", "scale"),
    ("CausalAttention_0", "query_proj", "kernel"): ("attn", "query", "kernel"),
    ("CausalAttention_0", "key_proj", "kernel"): ("attn", "key", "kernel"),
    ("CausalAttention_0", "value_proj", "kernel"): ("attn", "value", "kernel"),
    ("CausalAttention_0", "residual_out", "kernel"): ("attn", "out", "kernel"),
    ("MLPBlock_0", "fc_in", "kernel"): ("mlp", "wi", "kernel"),
    ("MLPBlock_0", "fc_residual", "kernel"): ("mlp", "wo", "kernel"),
}


def convert_reference_params(ref: dict, scan_layers: bool = True) -> dict:
    """Reference (fattorib/ZeRO-transformer) param tree -> this framework's.

    ``ref`` is the nested dict from the reference's extracted-params msgpack
    (``torch_compatability/extract_msgpack.py``); an outer ``params`` wrapper
    is tolerated. Every reference leaf must be consumed and every expected
    leaf present — unknown or missing names raise instead of silently
    dropping weights.
    """
    from flax.traverse_util import flatten_dict, unflatten_dict

    ref = dict(ref.get("params", ref))
    block_keys = sorted(
        (k for k in ref if k.startswith("TransformerBlock_")),
        key=lambda s: int(s.rsplit("_", 1)[1]),
    )
    if not block_keys:
        raise ValueError("no TransformerBlock_* entries: not a reference params tree")
    expected_top = set(block_keys) | {"wte", "LayerNorm_0"}
    unknown = set(ref) - expected_top
    if unknown:
        raise ValueError(f"unrecognized reference entries: {sorted(unknown)}")

    out = {
        ("wte", "embedding"): np.asarray(ref["wte"]["embedding"]),
        ("ln_f", "scale"): np.asarray(ref["LayerNorm_0"]["scale"]),
    }
    stacked: dict = {dst: [] for dst in _REF_BLOCK_MAP.values()}
    for bk in block_keys:
        flat = flatten_dict(ref[bk])
        extra = set(flat) - set(_REF_BLOCK_MAP)
        missing = set(_REF_BLOCK_MAP) - set(flat)
        if extra or missing:
            raise ValueError(
                f"{bk}: unrecognized leaves {sorted(extra)} / missing {sorted(missing)}"
            )
        for src, dst in _REF_BLOCK_MAP.items():
            stacked[dst].append(np.asarray(flat[src]))
    if scan_layers:
        for dst, arrs in stacked.items():
            out[("blocks",) + dst] = np.stack(arrs)
    else:
        for dst, arrs in stacked.items():
            for i, a in enumerate(arrs):
                out[(f"block_{i}",) + dst] = a
    return unflatten_dict(out)


def convert_to_reference_params(params: dict) -> dict:
    """This framework's param tree -> the reference's extracted-params
    layout (exact inverse of ``convert_reference_params``; round-tripping
    through it is the identity, tested).

    Completes the interchange symmetry: the reference exports its
    checkpoints outward (``torch_compatability/flax_to_pytorch.py:70-117``);
    this writes OUR checkpoints into the reference's msgpack layout —
    torch-free, loadable by the reference's own flax tooling.

    Only the reference's architecture family converts (GPT-2+ALiBi: tied
    embeddings, scale-only norms, bias-free square attention, dense
    gelu MLP). Leaves with no reference counterpart (swiglu gate, untied
    lm_head, MoE experts, learned-position wpe) raise — a silent drop
    would write a checkpoint that loads but computes a different function.
    NOTE the layout alone cannot distinguish RMSNorm from LayerNorm (both
    store one ``scale``); use the CLI's ``--model`` check (or your own
    config) to guard that.
    """
    from flax.traverse_util import flatten_dict, unflatten_dict

    params = dict(params.get("params", params))
    inv = {dst: src for src, dst in _REF_BLOCK_MAP.items()}
    flat = {k: np.asarray(v) for k, v in flatten_dict(params).items()}

    out: dict = {}
    consumed = set()
    for src, dst in (
        (("wte", "embedding"), ("wte", "embedding")),
        (("ln_f", "scale"), ("LayerNorm_0", "scale")),
    ):
        if src not in flat:
            raise ValueError(f"params tree has no {'/'.join(src)} leaf")
        out[dst] = flat[src]
        consumed.add(src)

    per_block: dict = {}

    def emit(i: int, sub: tuple, arr: np.ndarray) -> None:
        src = inv.get(sub)
        if src is None:
            raise ValueError(
                f"block leaf {'/'.join(sub)} has no reference counterpart "
                "(the reference family is GPT-2+ALiBi: tied embeddings, "
                "scale-only norms, dense gelu MLP)"
            )
        out[(f"TransformerBlock_{i}",) + src] = arr
        per_block.setdefault(i, set()).add(sub)

    n_layers = 0
    if any(k[0] == "blocks" for k in flat):  # stacked nn.scan layout
        for key, arr in flat.items():
            if key[0] != "blocks":
                continue
            for i in range(arr.shape[0]):
                emit(i, key[1:], arr[i])
            n_layers = max(n_layers, arr.shape[0])
            consumed.add(key)
    else:  # per-block layout
        for key, arr in flat.items():
            if not key[0].startswith("block_"):
                continue
            suffix = key[0].rsplit("_", 1)[1]
            if not suffix.isdigit():
                raise ValueError(
                    f"top-level entry {key[0]!r} is not a block_<i> layer "
                    "of this framework's per-block layout"
                )
            i = int(suffix)
            emit(i, key[1:], arr)
            n_layers = max(n_layers, i + 1)
            consumed.add(key)
    if n_layers == 0:
        raise ValueError("no blocks/block_i entries: not this framework's params tree")
    # per-block completeness: MISSING leaves (a truncated tree, a gap in the
    # block_i indices) must raise like extra ones do — an incomplete
    # reference checkpoint would load and compute a different function
    for i in range(n_layers):
        gap = set(inv) - per_block.get(i, set())
        if gap:
            names = sorted("/".join(s) for s in gap)
            raise ValueError(f"block {i}: missing leaves {names}")

    leftovers = set(flat) - consumed
    if leftovers:
        names = sorted("/".join(k) for k in leftovers)
        raise ValueError(
            f"leaves with no reference counterpart: {names} — only the "
            "GPT-2+ALiBi family (tied head, dense MLP) exports to the "
            "reference layout"
        )
    d = out[("wte", "embedding")].shape[1]
    for i in range(n_layers):
        for proj in ("query_proj", "key_proj", "value_proj", "residual_out"):
            shape = out[(f"TransformerBlock_{i}", "CausalAttention_0", proj, "kernel")].shape
            if shape != (d, d):
                raise ValueError(
                    f"TransformerBlock_{i}/{proj} kernel {shape} is not square "
                    f"[{d},{d}] — GQA/MQA models have no reference counterpart"
                )
    return unflatten_dict(out)


def _cmd_extract(args) -> None:
    import orbax.checkpoint as ocp

    from zero_transformer_tpu.checkpoint import export_params_msgpack

    directory = Path(args.checkpoint_dir).absolute()
    with ocp.CheckpointManager(directory) as mgr:
        step = args.step if args.step is not None else mgr.latest_step()
        if step is None:
            raise SystemExit(f"no checkpoints under {directory}")
        # structure-agnostic raw read; keep only params
        restored = mgr.restore(step, args=ocp.args.Composite(state=ocp.args.StandardRestore()))
    state = restored["state"]
    params = state["params"] if isinstance(state, dict) else state.params
    out = export_params_msgpack(params, args.out)
    n = sum(int(np.prod(x.shape)) for x in jax.tree.leaves(params))
    print(f"wrote {n:,} params (step {step}) -> {out}")


def _cmd_extend(args) -> None:
    from flax.serialization import msgpack_serialize

    from zero_transformer_tpu.checkpoint import import_params_msgpack
    from zero_transformer_tpu.utils.surgery import extend_depth, num_layers

    params = import_params_msgpack(args.params)
    old = num_layers(params)
    params = extend_depth(params, args.layers)
    Path(args.out).write_bytes(msgpack_serialize(params))
    print(f"extended {old} -> {args.layers} layers -> {args.out}")


def _cmd_upcycle(args) -> None:
    import jax
    import numpy as np
    from flax.serialization import msgpack_serialize

    from zero_transformer_tpu.checkpoint import import_params_msgpack
    from zero_transformer_tpu.utils.surgery import is_stacked, stack_blocks, upcycle_moe

    params = import_params_msgpack(args.params)
    if not is_stacked(params):
        params = stack_blocks(params)
    params = upcycle_moe(params, args.experts)
    Path(args.out).write_bytes(
        msgpack_serialize(jax.tree.map(np.asarray, params))
    )
    print(f"upcycled dense -> {args.experts} experts -> {args.out}")


def _cmd_import_reference(args) -> None:
    import jax.numpy as jnp
    from flax.serialization import msgpack_restore, msgpack_serialize

    from zero_transformer_tpu.config import model_config
    from zero_transformer_tpu.models import Transformer
    from zero_transformer_tpu.parallel.sharding import unbox

    ref = msgpack_restore(Path(args.params).read_bytes())
    cfg = model_config(args.model)
    params = convert_reference_params(ref, scan_layers=cfg.scan_layers)

    # validate every leaf against the target architecture's init shapes —
    # a wrong --model (depth, width, vocab) fails HERE, not at load time
    shapes = jax.eval_shape(
        lambda r: Transformer(cfg).init(r, jnp.zeros((1, 8), jnp.int32)),
        jax.random.PRNGKey(0),
    )["params"]
    shapes = unbox(shapes)
    flat_got = jax.tree_util.tree_flatten_with_path(params)[0]
    flat_want = dict(jax.tree_util.tree_flatten_with_path(shapes)[0])
    for path, leaf in flat_got:
        want = flat_want.get(path)
        name = "/".join(str(getattr(k, "key", k)) for k in path)
        if want is None:
            raise SystemExit(f"converted leaf {name} not in {args.model} params")
        if tuple(want.shape) != tuple(leaf.shape):
            raise SystemExit(
                f"{name}: shape {tuple(leaf.shape)} != {args.model}'s {tuple(want.shape)}"
            )
    missing = set(flat_want) - {p for p, _ in flat_got}
    if missing:
        names = sorted("/".join(str(getattr(k, 'key', k)) for k in m) for m in missing)
        raise SystemExit(f"{args.model} params missing from conversion: {names}")

    Path(args.out).write_bytes(msgpack_serialize(params))
    n = sum(int(np.prod(x.shape)) for x in jax.tree.leaves(params))
    print(f"converted {n:,} reference params ({args.model}) -> {args.out}")


def _cmd_to_reference(args) -> None:
    from flax.serialization import msgpack_serialize

    from zero_transformer_tpu.checkpoint import import_params_msgpack

    params = import_params_msgpack(args.params)
    if args.model:
        from zero_transformer_tpu.config import model_config

        cfg = model_config(args.model)
        bad = [
            f"{field}={got!r} (reference: {want!r})"
            for field, got, want in (
                ("norm", cfg.norm, "layernorm"),
                ("position", cfg.position, "alibi"),
                ("activation", cfg.activation, "gelu"),
                ("tie_embeddings", cfg.tie_embeddings, True),
            )
            if got != want
        ]
        if bad:
            raise SystemExit(
                f"{args.model} is outside the reference family: {'; '.join(bad)}"
            )
    # unwrap once HERE: the converter tolerates an outer "params" wrapper,
    # so the layout detection and round-trip comparison below must see the
    # same unwrapped tree it converts
    params = dict(params.get("params", params))
    ref = convert_to_reference_params(params)
    # round-trip safety: the emitted layout must read back to the SAME tree
    # through the importer — the two maps must stay exact inverses. A real
    # check, not an assert: it must survive python -O
    back = convert_reference_params(
        ref, scan_layers=any(k == "blocks" for k in params)
    )
    for (pa, a), (pb, b) in zip(
        jax.tree_util.tree_flatten_with_path(params)[0],
        jax.tree_util.tree_flatten_with_path(back)[0],
    ):
        if pa != pb or not np.array_equal(
            np.asarray(a), np.asarray(b), equal_nan=True
        ):  # equal_nan: a diverged run's NaN weights still convert exactly
            raise SystemExit(f"round-trip mismatch at {pa}: refusing to write")
    Path(args.out).write_bytes(msgpack_serialize(ref))
    n = sum(int(np.prod(x.shape)) for x in jax.tree.leaves(ref))
    print(f"wrote {n:,} params in reference layout -> {args.out}")


def _cmd_quantize(args) -> None:
    from zero_transformer_tpu.checkpoint import (
        export_params_msgpack,
        import_params_msgpack,
    )
    from zero_transformer_tpu.models.quant import quantize_params

    params = import_params_msgpack(args.params)
    out = export_params_msgpack(quantize_params(params), args.out)
    before = Path(args.params).stat().st_size
    after = Path(args.out).stat().st_size
    print(f"quantized {before:,} -> {after:,} bytes ({after / before:.2f}x) -> {out}")


def _cmd_inspect(args) -> None:
    from zero_transformer_tpu.checkpoint import import_params_msgpack
    from zero_transformer_tpu.utils.surgery import is_stacked, num_layers

    params = import_params_msgpack(args.params)
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    total = 0
    for path, leaf in flat:
        name = "/".join(str(getattr(k, "key", k)) for k in path)
        print(f"{name:60s} {str(leaf.dtype):10s} {tuple(leaf.shape)}")
        total += int(np.prod(leaf.shape))
    print(
        f"-- {total:,} params, {num_layers(params)} layers "
        f"({'stacked' if is_stacked(params) else 'per-block'} layout)"
    )


def main(argv=None) -> None:
    p = argparse.ArgumentParser(prog="zero_transformer_tpu.export", description=__doc__)
    sub = p.add_subparsers(dest="cmd", required=True)

    ex = sub.add_parser("extract", help="orbax checkpoint -> params msgpack")
    ex.add_argument("--checkpoint-dir", required=True)
    ex.add_argument("--step", type=int, default=None)
    ex.add_argument("--out", required=True)
    ex.set_defaults(fn=_cmd_extract)

    et = sub.add_parser("extend", help="depth-extend params (Gopher G.3.3 warm start)")
    et.add_argument("--params", required=True)
    et.add_argument("--layers", type=int, required=True)
    et.add_argument("--out", required=True)
    et.set_defaults(fn=_cmd_extend)

    up = sub.add_parser(
        "upcycle", help="dense params -> MoE warm start (sparse upcycling)"
    )
    up.add_argument("--params", required=True)
    up.add_argument("--experts", type=int, required=True)
    up.add_argument("--out", required=True)
    up.set_defaults(fn=_cmd_upcycle)

    ins = sub.add_parser("inspect", help="list tensors in a params msgpack")
    ins.add_argument("--params", required=True)
    ins.set_defaults(fn=_cmd_inspect)

    qz = sub.add_parser(
        "quantize",
        help="params msgpack -> weight-only int8 serving msgpack (the "
             "conversion serve/evalharness --quantize run, paid once; "
             "~4x smaller artifact from f32, ~2x from bf16)",
    )
    qz.add_argument("--params", required=True)
    qz.add_argument("--out", required=True)
    qz.set_defaults(fn=_cmd_quantize)

    tr = sub.add_parser(
        "to-reference",
        help="this framework's params msgpack -> the reference's "
             "extracted-params layout (inverse of import-reference, "
             "round-trip-verified)",
    )
    tr.add_argument("--params", required=True)
    tr.add_argument("--model", default=None,
                    help="optional zoo name: reject configs outside the "
                         "reference family (rmsnorm/rope/swiglu/untied)")
    tr.add_argument("--out", required=True)
    tr.set_defaults(fn=_cmd_to_reference)

    ir = sub.add_parser(
        "import-reference",
        help="reference (fattorib/ZeRO-transformer) params msgpack -> this "
             "framework's layout, shape-validated against a zoo model",
    )
    ir.add_argument("--params", required=True,
                    help="the reference's extracted-params msgpack")
    ir.add_argument("--model", required=True, help="target zoo name")
    ir.add_argument("--out", required=True)
    ir.set_defaults(fn=_cmd_import_reference)

    args = p.parse_args(argv)
    args.fn(args)


if __name__ == "__main__":
    main()
