"""Continuous-batching serving engine.

The orchestration layer above the jitted decode path: a slot-based KV cache
(``slots``), a request scheduler with deadlines/cancellation/backpressure
(``engine``), a streaming SSE front end (``server``), the shared
incremental detokenizer (``detok``), and the serving resilience layer
(``resilience``: lifecycle state machine, decode-tick supervision with a
circuit breaker, graceful drain, hot weight reload, deadline-aware load
shedding, serving chaos harness), and the fleet tier above them all
(``router``: replica registry with health probing and ejection,
prefix-aware + least-loaded routing, mid-stream failover, rolling fleet
reload). See docs/DESIGN.md § Serving engine, docs/SERVING.md § Fleet
router, and docs/RESILIENCE.md § Serving resilience.
"""
from zero_transformer_tpu.serving.detok import StreamDecoder
from zero_transformer_tpu.serving.engine import (
    CANCELLED,
    DONE,
    EXPIRED,
    FAILED,
    MIGRATED,
    QUEUED,
    REJECTED,
    ROLES,
    RUNNING,
    Request,
    RequestHandle,
    ServingEngine,
)
from zero_transformer_tpu.serving.prefix_cache import (
    PagedPrefixIndex,
    PrefixCache,
)
from zero_transformer_tpu.serving.qos import (
    BROWNOUT_RUNGS,
    QOS_CLASSES,
    BrownoutController,
    ClassQueue,
    QosClassConfig,
    QosPolicy,
    TenantBuckets,
    TokenBucket,
    rung_at_least,
)
from zero_transformer_tpu.serving.resilience import (
    DEGRADED,
    DRAINING,
    READY,
    STARTING,
    STOPPED,
    CircuitBreaker,
    Lifecycle,
    ReloadError,
    ServeFault,
    ServingChaosMonkey,
)
from zero_transformer_tpu.serving.router import (
    EJECTED,
    PrefixAffinity,
    Replica,
    ReplicaRegistry,
    RouterServer,
    chunk_prefix_key,
    pick_decode_replica,
    pick_replica,
    run_router,
)
from zero_transformer_tpu.serving.server import ServingServer, run_server
from zero_transformer_tpu.serving.slots import (
    PagedKVCache,
    PagePool,
    SlotKVCache,
    page_span_from_wire,
    page_span_to_wire,
    vectorize_index,
)

__all__ = [
    "BROWNOUT_RUNGS",
    "BrownoutController",
    "ClassQueue",
    "DEGRADED",
    "DRAINING",
    "EJECTED",
    "QOS_CLASSES",
    "QosClassConfig",
    "QosPolicy",
    "TenantBuckets",
    "TokenBucket",
    "rung_at_least",
    "READY",
    "STARTING",
    "STOPPED",
    "CircuitBreaker",
    "Lifecycle",
    "PrefixAffinity",
    "Replica",
    "ReplicaRegistry",
    "RouterServer",
    "chunk_prefix_key",
    "pick_decode_replica",
    "pick_replica",
    "run_router",
    "PagedKVCache",
    "PagedPrefixIndex",
    "PagePool",
    "PrefixCache",
    "ReloadError",
    "ServeFault",
    "ServingChaosMonkey",
    "CANCELLED",
    "DONE",
    "EXPIRED",
    "FAILED",
    "MIGRATED",
    "QUEUED",
    "REJECTED",
    "ROLES",
    "RUNNING",
    "page_span_from_wire",
    "page_span_to_wire",
    "Request",
    "RequestHandle",
    "ServingEngine",
    "ServingServer",
    "SlotKVCache",
    "StreamDecoder",
    "run_server",
    "vectorize_index",
]
