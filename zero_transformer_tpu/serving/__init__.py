"""Continuous-batching serving engine.

The orchestration layer above the jitted decode path: a slot-based KV cache
(``slots``), a request scheduler with deadlines/cancellation/backpressure
(``engine``), a streaming SSE front end (``server``), and the shared
incremental detokenizer (``detok``). See docs/DESIGN.md § Serving engine.
"""
from zero_transformer_tpu.serving.detok import StreamDecoder
from zero_transformer_tpu.serving.engine import (
    CANCELLED,
    DONE,
    EXPIRED,
    FAILED,
    QUEUED,
    REJECTED,
    RUNNING,
    Request,
    RequestHandle,
    ServingEngine,
)
from zero_transformer_tpu.serving.server import ServingServer, run_server
from zero_transformer_tpu.serving.slots import SlotKVCache, vectorize_index

__all__ = [
    "CANCELLED",
    "DONE",
    "EXPIRED",
    "FAILED",
    "QUEUED",
    "REJECTED",
    "RUNNING",
    "Request",
    "RequestHandle",
    "ServingEngine",
    "ServingServer",
    "SlotKVCache",
    "StreamDecoder",
    "run_server",
    "vectorize_index",
]
