"""Streaming HTTP front end for the continuous-batching engine.

Stdlib-only (``http.server`` threads + SSE) so the serving surface works in
this image without extra dependencies — the reference's only UI was a
CUDA+gradio app (reference ``app.py``). Endpoints:

- ``POST /generate``: JSON body ``{"prompt": str | "tokens": [int],
  "max_new_tokens": int, "seed": int, "timeout": float, "stream": bool}``.
  With ``stream`` (default true) the response is ``text/event-stream``: one
  ``data: {"token": id, "text": piece}`` event per token — ``"text"`` is
  the empty string while the detokenizer buffers a piece mid-UTF-8, so
  every token id is on the wire (the fleet router's mid-stream resume
  point) and joining ``e["text"]`` still reconstructs the full text — and
  a final ``data: {"done": true, "status": ..., "text": full}``. Without, a
  single JSON document. Backpressure maps to HTTP 429 (queue full) / 400
  (invalid request).
- ``GET /healthz``: the engine's LIFECYCLE, with real status codes — 200
  only when READY; 503 while starting, degraded (breaker open), draining,
  or stopped, so a load balancer routes around a sick replica. Body:
  ``{"state", "uptime_s", "reloads", "breaker_open", ...}``.
- ``GET /metrics``: content-negotiated. The default stays the JSON snapshot
  (TTFT/ITL percentiles — with a pure-decode ``itl_decode_ms_*`` split
  isolating chunked-prefill interference — tokens/s, rejects, prefix-cache
  hit/miss/entry counters, compiled prefill-bucket gauge, resilience
  counters); an ``Accept`` header naming ``text/plain`` or ``openmetrics``
  (what a Prometheus scraper sends), or ``?format=prometheus``, gets the
  text exposition format backed by the engine's fixed-bucket histograms —
  O(buckets) per scrape, never the tick lock (docs/OBSERVABILITY.md).
- ``POST /admin/reload``: hot weight reload — load a standby msgpack tree
  off the tick thread, validate, swap between ticks without dropping a
  slot (also wired to SIGHUP by ``install_signal_handlers``).
- ``POST /admin/profile``: ``{"ticks": N}`` captures a ``jax.profiler``
  trace of the next N scheduler ticks into the engine's obs directory
  (same loopback/bearer-token gate as reload; 409 while DRAINING or when a
  capture is already running).

Request correlation: every request carries an id — inbound ``X-Request-Id``
(or body ``request_id``) when the caller supplies one for cross-service
correlation, generated at admission otherwise — echoed as an
``X-Request-Id`` response header on every /generate response (SSE and JSON,
success and rejection) and as ``request_id`` in the final SSE event. The
same id keys the request's span tree in the engine's tracer.

One scheduler thread drives ``engine.step()``; HTTP handler threads only
``submit()`` and drain per-request queues, so a slow client never stalls
decode for everyone else (the whole point of continuous batching).
Retryable rejections (drain, shed, breaker) map to 503 + ``Retry-After``;
request bodies are bounded (413) so an oversized POST can't balloon the
stdlib handler. SIGTERM (``install_signal_handlers``) begins a graceful
drain: admission closes, in-flight streams finish up to the drain
deadline, then the process exits 0.
"""
from __future__ import annotations

import http.client
import json
import math
import queue as queue_mod
import select
import signal
import socket
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional
from urllib.parse import urlsplit

from zero_transformer_tpu.serving.detok import StreamDecoder, decode_tokens
from zero_transformer_tpu.serving.engine import (
    FAILED,
    MIGRATED,
    REJECTED,
    RequestHandle,
    ServingEngine,
)
from zero_transformer_tpu.serving.resilience import READY, STOPPED, ReloadError
from zero_transformer_tpu.serving.slots import (
    page_span_from_wire,
    page_span_to_wire,
)

# how long an SSE handler blocks on the next token before re-checking that
# the client is still connected (a request parked in the admission queue, or
# a half-open peer that will never RST, produces no write to fail on)
_LIVENESS_POLL_S = 0.5


def _client_gone(conn) -> bool:
    """True when the peer has closed its end: for SSE the client sends
    nothing after the POST body, so a READABLE socket whose peek returns
    b'' is a FIN. Half-open peers (host gone, no FIN/RST) still need the
    write-failure path — this catches the common orderly close."""
    try:
        readable, _, _ = select.select([conn], [], [], 0)
        if readable:
            return conn.recv(1, socket.MSG_PEEK) == b""
    except OSError:
        return True
    return False


class ServingServer:
    """Own the HTTP server + the engine's scheduler thread."""

    def __init__(self, engine: ServingEngine, tokenizer, host: str = "127.0.0.1",
                 port: int = 8000, max_body_bytes: int = 1 << 20,
                 reload_source=None, admin_token: Optional[str] = None,
                 max_ingest_bytes: int = 256 << 20):
        self.engine = engine
        self.tokenizer = tokenizer
        self.max_body_bytes = max_body_bytes
        # /ingest bodies carry raw KV pages — bounded separately from the
        # JSON request bound (a real span is MBs where a prompt is KBs)
        self.max_ingest_bytes = max_ingest_bytes
        # imported streams awaiting their /attach (rid -> (handle,
        # ingested_at)); the attach POPS, so a stream is consumed exactly
        # once, and a TTL sweep cancels orphans (router died between the
        # ship ack and the attach) so they cannot burn decode capacity or
        # leak handles forever
        self._pending_streams: Dict[str, tuple] = {}
        self._streams_lock = threading.Lock()
        self.attach_ttl_s = 300.0
        # page shipper: the engine's tick thread enqueues (payload, target,
        # on_done); this thread serializes + POSTs to <target>/ingest so
        # the tick thread never blocks on a peer's socket
        self._ship_queue: "queue_mod.Queue" = queue_mod.Queue()
        self._ship_thread = threading.Thread(
            target=self._ship_loop, name="serve-shipper", daemon=True
        )
        if engine.page_shipper is None:
            engine.page_shipper = self._enqueue_ship
        # reload source for SIGHUP / POST /admin/reload: a msgpack path, or
        # a loader callable — called with the request's path when one is
        # given, with no args otherwise (serve.py's loader replays the full
        # startup path: import -> quantize -> TP shard)
        self.reload_source = reload_source
        # /admin/* access: loopback peers always; non-loopback only with
        # this bearer token (weight swapping must not be open to any peer
        # that can reach a --host 0.0.0.0 port)
        self.admin_token = admin_token
        self._stop = threading.Event()
        self._scheduler = threading.Thread(
            target=engine.run, args=(self._stop,), name="serve-scheduler",
            daemon=True,
        )
        outer = self

        class Handler(BaseHTTPRequestHandler):
            # quiet by default; the engine's metrics logger is the log surface
            def log_message(self, fmt, *args):  # noqa: A003
                pass

            def _json(self, code: int, obj, headers=None) -> None:
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for name, value in (headers or {}).items():
                    self.send_header(name, value)
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802
                path, _, query = self.path.partition("?")
                if path == "/healthz":
                    self._json(*outer._healthz())
                elif path == "/admin/spans":
                    # the fleet-trace stitch seam (PR 15): the router pulls
                    # this replica's span tail for one request id and maps
                    # it onto its own clock — admin-gated like every other
                    # /admin route (span attrs can carry prompt-adjacent
                    # metadata)
                    if not outer._admin_allowed(self):
                        self._json(403, {"error": "admin endpoint: loopback "
                                                  "or bearer token required"})
                        return
                    self._json(*outer._admin_spans(query))
                elif path == "/metrics":
                    accept = self.headers.get("Accept") or ""
                    if (
                        "format=prometheus" in query
                        or "text/plain" in accept
                        or "openmetrics" in accept
                    ):
                        # the Prometheus scrape path: its Accept header
                        # names text/plain;version=0.0.4 (and/or
                        # openmetrics); JSON dashboards keep the default
                        body = outer.engine.prometheus_text().encode()
                        self.send_response(200)
                        self.send_header(
                            "Content-Type",
                            "text/plain; version=0.0.4; charset=utf-8",
                        )
                        self.send_header("Content-Length", str(len(body)))
                        self.end_headers()
                        self.wfile.write(body)
                    else:
                        self._json(200, outer.engine.metrics_snapshot())
                else:
                    self._json(404, {"error": f"no route {self.path}"})

            def do_POST(self):  # noqa: N802
                if self.path not in (
                    "/generate", "/attach", "/ingest",
                    "/admin/reload", "/admin/profile",
                    "/admin/migrate", "/admin/migrate_all",
                    "/admin/brownout",
                ):
                    self._json(404, {"error": f"no route {self.path}"})
                    return
                try:
                    length = int(self.headers.get("Content-Length", 0))
                except ValueError:
                    self._json(400, {"error": "bad Content-Length"})
                    return
                if self.path == "/ingest":
                    # binary page-span body — its own (much larger) bound,
                    # and no JSON parse
                    if length < 0 or length > outer.max_ingest_bytes:
                        self.close_connection = True
                        self._json(413 if length > 0 else 400, {
                            "error": (
                                f"ingest body must be 0..{outer.max_ingest_bytes} bytes"
                            ),
                        })
                        return
                    outer._ingest(self, self.rfile.read(length))
                    return
                if length < 0:
                    # rfile.read(-1) would read until EOF — unbounded, the
                    # exact balloon the body bound exists to prevent
                    self._json(400, {"error": "bad Content-Length"})
                    return
                if length > outer.max_body_bytes:
                    # bound BEFORE reading: an oversized POST must not
                    # balloon the stdlib handler's memory. The unread body
                    # would desynchronize the connection — close it.
                    self.close_connection = True
                    self._json(413, {
                        "error": f"body exceeds {outer.max_body_bytes} bytes",
                    })
                    return
                try:
                    req = json.loads(self.rfile.read(length) or b"{}")
                except (ValueError, json.JSONDecodeError):
                    self._json(400, {"error": "malformed JSON body"})
                    return
                if not isinstance(req, dict):
                    # valid JSON but not an object ([1,2], "x") — still the
                    # client's error, not a handler-thread traceback
                    self._json(400, {"error": "body must be a JSON object"})
                    return
                if self.path.startswith("/admin/"):
                    if not outer._admin_allowed(self):
                        self._json(403, {"error": "admin endpoint: loopback "
                                                  "or bearer token required"})
                        return
                    if self.path == "/admin/reload":
                        self._json(*outer._reload(req))
                    elif self.path == "/admin/migrate":
                        self._json(*outer._migrate(req))
                    elif self.path == "/admin/migrate_all":
                        self._json(*outer._migrate_all(req))
                    elif self.path == "/admin/brownout":
                        self._json(*outer._brownout(req))
                    else:
                        self._json(*outer._profile(req))
                elif self.path == "/attach":
                    outer._attach(self, req)
                else:
                    outer._generate(self, req)

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    # ------------------------------------------------------------ lifecycle

    def start(self, start_scheduler: bool = True) -> None:
        """``start_scheduler=False`` serves HTTP with the engine still
        STARTING (tests assert /healthz is 503 before readiness; a real
        deployment would use it to finish warmup before taking traffic) —
        call ``start_scheduler()`` to go READY."""
        if not self._ship_thread.ident:
            self._ship_thread.start()
        if start_scheduler:
            self.start_scheduler()
        self._server_thread = threading.Thread(
            target=self._httpd.serve_forever, name="serve-http", daemon=True
        )
        self._server_thread.start()

    def start_scheduler(self) -> None:
        if not self._ship_thread.ident:
            self._ship_thread.start()
        if not self._scheduler.ident:
            self._scheduler.start()

    def serve_forever(self) -> None:
        if not self._ship_thread.ident:
            self._ship_thread.start()
        self.start_scheduler()
        try:
            self._httpd.serve_forever()
        finally:
            self.stop()

    def stop(self) -> None:
        self._stop.set()
        self._httpd.shutdown()

    # ------------------------------------------------------------ resilience

    def _healthz(self):
        """(code, body) for /healthz: 200 ONLY when the engine is READY and
        its scheduler thread is alive — warming up, degraded, draining, and
        stopped all answer 503 so a load balancer stops routing here."""
        # orphan sweep rides the health poll (routers probe every replica
        # continuously), so a replica that stops receiving ingest/attach
        # traffic still cancels un-attached imports at the TTL
        self._sweep_pending_streams()
        state = self.engine.lifecycle.state
        alive = self._scheduler.is_alive() or not self._scheduler.ident
        if not alive and state != STOPPED:
            state = "scheduler dead"
        ok = state == READY and alive
        return (200 if ok else 503), {
            "status": "ok" if ok else state,
            "state": state,
            # this replica's monotonic clock AT ANSWER TIME: the router
            # brackets the probe with its own clock and estimates the
            # per-process offset (NTP-style midpoint) that lets it map
            # this replica's span timestamps onto one fleet timeline
            "clock_monotonic": self.engine.now(),
            "uptime_s": round(self.engine.lifecycle.uptime_s, 3),
            "reloads": self.engine.stats["reloads"],
            "breaker_open": self.engine._breaker.open,
            "slots": self.engine.n_slots,
            "active": self.engine.active_count,
            "prefilling": len(self.engine._prefilling),
            "queued": self.engine.queue_depth,
            # the fleet router's admission inputs (ISSUE 9): everything its
            # least-loaded policy needs rides the same cheap health poll —
            # one GET instead of a /metrics scrape per routing refresh
            "itl_ewma_ms": round(
                (self.engine._itl_ewma.value or 0.0) * 1e3, 4
            ),
            "queue_depth": self.engine.queue_depth,
            "active_slots": self.engine.active_count,
            "free_pages": self.engine.free_pages,
            # disaggregation inputs (ISSUE 12): the router's role-aware
            # placement reads both off the same cheap poll, and the
            # page-pool pressure stats ride along so the router can mirror
            # them as per-replica gauges without a /metrics scrape
            "role": self.engine.role,
            "kv_layout": self.engine.kv_layout,
            "draft_k": self.engine.draft_k,
            "migrations_in_flight": self.engine.migrations_in_flight,
            "page_faults": self.engine.stats["page_faults"],
            "cow_copies": (
                self.engine.slots.cow_copies
                if self.engine.kv_layout == "paged" else 0
            ),
            # overload-isolation inputs (ISSUE 18): the fleet brownout
            # controller reads the rung it last pushed back off the same
            # poll (convergence check), and per-class queue depths let the
            # router see WHICH class is backed up, not just how much
            "brownout_rung": self.engine.brownout_rung,
            "queue_by_class": self.engine._queue.counts(),
        }

    def _admin_allowed(self, handler) -> bool:
        peer = handler.client_address[0]
        if peer in ("127.0.0.1", "::1", "::ffff:127.0.0.1"):
            return True
        if self.admin_token:
            auth = handler.headers.get("Authorization", "")
            return auth == f"Bearer {self.admin_token}"
        return False

    def _admin_spans(self, query: str):
        """(code, body) for GET /admin/spans?request_id=<rid>[&tail=N]:
        this replica's span tail for one request track (or the whole ring
        tail with no request_id), plus the engine clock reading the router
        needs to place these spans on the fleet timeline."""
        from urllib.parse import parse_qs

        params = parse_qs(query)
        rid = (params.get("request_id") or [None])[0]
        try:
            tail = int((params.get("tail") or [2000])[0])
        except (TypeError, ValueError):
            return 400, {"error": "tail must be an integer"}
        spans = self.engine.tracer.track_dicts(
            track=rid if rid else None, tail=max(1, min(tail, 20000)),
        )
        return 200, {
            "request_id": rid or "",
            "clock_monotonic": self.engine.now(),
            "role": self.engine.role,
            "spans": spans,
            "spans_dropped": self.engine.tracer.dropped,
        }

    def _reload(self, req: dict):
        """(code, body) for POST /admin/reload: load a standby tree in THIS
        handler thread (off the tick thread), validate, swap between ticks.
        409 on a corrupt/mismatched artifact — the engine stays READY on
        the old weights.

        A request path is handed to the CONFIGURED loader when one exists
        (so int8-quantized / TP-sharded servers prepare the reloaded tree
        exactly like the startup tree); the bare msgpack import is only the
        fallback for servers configured without a loader."""
        path = req.get("params")
        if callable(self.reload_source):
            loader = self.reload_source
            source = (lambda: loader(path)) if path else loader
        elif path or isinstance(self.reload_source, str):
            load_path = path or self.reload_source

            def source():
                from zero_transformer_tpu.checkpoint import import_params_msgpack

                return import_params_msgpack(load_path)
        else:
            return 400, {"error": "no reload source: pass {\"params\": <path>}"}
        try:
            info = self.engine.reload_params(source)
        except ReloadError as exc:
            return 409, {
                "error": str(exc),
                "state": self.engine.lifecycle.state,
                "reloads": self.engine.stats["reloads"],
            }
        # wait on THIS reload's swap event (not a shared latest-reload flag:
        # concurrent staging must not let one caller claim another's swap)
        swapped = info["swapped"].wait(timeout=30.0)
        return (200 if swapped else 202), {
            "reloaded": swapped,
            "reloads": self.engine.stats["reloads"],
            "state": self.engine.lifecycle.state,
        }

    def _profile(self, req: dict):
        """(code, body) for POST /admin/profile: stage a jax.profiler
        capture of the next N scheduler ticks, landing in the engine's obs
        directory next to the flight-recorder dumps. 202 (the capture runs
        asynchronously on the tick thread); 409 while draining, when a
        capture is already in progress, or without an obs directory."""
        try:
            ticks = int(req.get("ticks", 20))
        except (TypeError, ValueError):
            return 400, {"error": "ticks must be an integer"}
        try:
            info = self.engine.request_profile(ticks)
        except ValueError as exc:
            return 400, {"error": str(exc)}
        except RuntimeError as exc:
            return 409, {"error": str(exc), "state": self.engine.lifecycle.state}
        return 202, {"accepted": True, **info}

    def _brownout(self, req: dict):
        """(code, body) for POST /admin/brownout: set this replica's
        brownout rung (``{"rung": "no_spec"}``). The fleet router's
        controller drives this on every transition; operators can also hit
        it directly to force or clear a rung. Idempotent — re-posting the
        current rung is a 200 no-op."""
        rung = req.get("rung")
        if not isinstance(rung, str):
            return 400, {"error": "rung must be a string"}
        try:
            info = self.engine.set_brownout(rung)
        except ValueError as exc:
            return 400, {"error": str(exc)}
        return 200, info

    # -------------------------------------------- disaggregation / migration

    def _enqueue_ship(self, payload: dict, target: str, on_done) -> None:
        """The engine's ``page_shipper`` seam: hand the export to the
        shipper thread and return immediately — the tick thread never
        blocks on a peer replica's socket."""
        self._ship_queue.put((payload, target, on_done))

    def _ship_loop(self) -> None:
        while not self._stop.is_set():
            try:
                item = self._ship_queue.get(timeout=0.2)
            except queue_mod.Empty:
                continue
            payload, target, on_done = item
            try:
                err = self._ship_once(payload, target)
            except Exception as exc:  # noqa: BLE001 — a shipper crash must fail ONE migration, not the thread
                err = f"{type(exc).__name__}: {exc}"
            on_done(err)

    def _ship_once(self, payload: dict, target: str) -> Optional[str]:
        """POST one page-span payload to ``<target>/ingest``. Returns None
        on an accepted ingest, else a reason string (the engine fails that
        migration retryably and the router falls back to recompute)."""
        blob = page_span_to_wire(payload)
        parts = urlsplit(target if "//" in target else f"http://{target}")
        host = parts.hostname or "127.0.0.1"
        port = parts.port or 80
        conn = http.client.HTTPConnection(host, port, timeout=30.0)
        try:
            conn.request(
                "POST", "/ingest", blob,
                {"Content-Type": "application/octet-stream",
                 "X-Request-Id": str(payload.get("request_id", ""))},
            )
            resp = conn.getresponse()
            body = resp.read()
            if resp.status != 200:
                try:
                    doc = json.loads(body or b"{}")
                except ValueError:
                    doc = {}
                return (
                    f"ingest at {target} returned {resp.status}: "
                    f"{doc.get('error', '')}"
                )
            return None
        except (OSError, http.client.HTTPException) as exc:
            return f"ship to {target} failed: {type(exc).__name__}: {exc}"
        finally:
            conn.close()

    def _ingest(self, handler, blob: bytes) -> None:
        """POST /ingest: accept a migrated stream's pages + carry. The
        imported handle parks in the pending-streams table until the
        router ATTACHES (tokens that decode meanwhile buffer in the
        handle's queue — nothing is lost, TTFT overlaps the attach)."""
        try:
            payload = page_span_from_wire(blob)
        except ValueError as exc:
            handler._json(400, {"error": f"bad page-span body: {exc}"})
            return
        handle = self.engine.import_stream(payload)
        if handle.status in (REJECTED, FAILED):
            code = 503 if handle.retryable else 409
            handler._json(code, {
                "error": handle.error, "status": handle.status,
                "request_id": handle.rid,
            }, headers={"X-Request-Id": handle.rid})
            return
        self._sweep_pending_streams()
        with self._streams_lock:
            displaced = self._pending_streams.pop(handle.rid, None)
            self._pending_streams[handle.rid] = (handle, time.monotonic())
        if displaced is not None:
            # duplicate rid (a re-shipped stream whose earlier ingest ack
            # was lost): the NEW import is the live one — cancel the
            # displaced handle so it cannot decode its budget unwatched
            displaced[0].cancel()
        handler._json(200, {
            "accepted": True, "request_id": handle.rid,
        }, headers={"X-Request-Id": handle.rid})

    def _sweep_pending_streams(self) -> None:
        """Cancel + drop imported streams nobody attached within the TTL:
        an orphan (its router died between ship ack and attach) must not
        decode its whole budget into the void or leak its handle."""
        cutoff = time.monotonic() - self.attach_ttl_s
        with self._streams_lock:
            stale = [
                rid for rid, (_, t0) in self._pending_streams.items()
                if t0 < cutoff
            ]
            dropped = [self._pending_streams.pop(rid) for rid in stale]
        for handle, _ in dropped:
            handle.cancel()

    def _attach(self, handler, req: dict) -> None:
        """POST /attach {"request_id"}: take over an imported stream's SSE.
        Pops the pending entry — a stream attaches exactly once; an unknown
        id is a clean 404 (the router then falls back to recompute)."""
        self._sweep_pending_streams()
        rid = str(req.get("request_id", ""))
        with self._streams_lock:
            handle, _ = self._pending_streams.pop(rid, (None, 0.0))
        if handle is None:
            handler._json(404, {
                "error": f"no pending stream {rid!r}", "request_id": rid,
            }, headers={"X-Request-Id": rid})
            return
        try:
            handler.send_response(200)
            handler.send_header("Content-Type", "text/event-stream")
            handler.send_header("Cache-Control", "no-cache")
            handler.send_header("X-Request-Id", handle.rid)
            handler.end_headers()
        except (BrokenPipeError, ConnectionResetError, OSError):
            # the attacher vanished between POST and headers: the entry is
            # already popped (attach is consume-once), so cancel — the
            # stream must not decode its budget into the void; the
            # router's retry gets a 404 and the recompute fallback covers
            handle.cancel()
            return
        self._stream_events(handler, handle)

    def _migrate(self, req: dict):
        """(code, body) for POST /admin/migrate {"request_id", "target"}:
        tag one live stream for migration. The export happens between
        ticks; the stream's open SSE ends with a ``migrated`` done event
        naming the target, which the router turns into an attach hop."""
        rid = str(req.get("request_id", ""))
        target = str(req.get("target", ""))
        if not rid or not target:
            return 400, {"error": "request_id and target are required"}
        if self.engine.request_migration(rid, target):
            return 202, {"requested": True, "request_id": rid,
                         "target": target}
        return 404, {"error": f"no live stream {rid!r}", "request_id": rid}

    def _migrate_all(self, req: dict):
        """(code, body) for POST /admin/migrate_all {"target"}: migrate
        every live stream (drain-as-migrate: rolling reload and scale-down
        use this instead of waiting out in-flight generations)."""
        target = str(req.get("target", ""))
        if not target:
            return 400, {"error": "target is required"}
        n = self.engine.request_migrate_all(target)
        return 202, {"requested": n, "target": target}

    def drain(self, deadline_s: Optional[float] = 30.0) -> None:
        """Begin a graceful drain and, once the engine reports STOPPED (or
        the deadline plus grace expires), shut the HTTP server down.
        ``deadline_s=None`` honors the engine contract — wait indefinitely
        for in-flight generations (no silent 10-second cutoff)."""
        self.engine.begin_drain(deadline_s)
        give_up = (
            None if deadline_s is None
            else time.monotonic() + deadline_s + 10.0
        )
        while self.engine.lifecycle.state != STOPPED and (
            give_up is None or time.monotonic() < give_up
        ):
            time.sleep(0.05)
        self.stop()

    def install_signal_handlers(
        self, drain_deadline_s: Optional[float] = 30.0
    ) -> None:
        """SIGTERM -> graceful drain (in a helper thread: the handler must
        return immediately); SIGHUP -> hot reload from ``reload_source``.
        The drain ends with ``stop()``, which returns the blocking
        ``serve_forever()`` caller — the process then exits 0, the contract
        an orchestrator's preemption hook expects."""

        def on_term(signum, frame):
            threading.Thread(
                target=self.drain, args=(drain_deadline_s,),
                name="serve-drain", daemon=True,
            ).start()

        def on_hup(signum, frame):
            if self.reload_source is None:
                return

            def _reload():
                try:
                    self._reload({})
                except Exception:
                    pass  # already counted/evented by the engine

            threading.Thread(target=_reload, name="serve-reload", daemon=True).start()

        signal.signal(signal.SIGTERM, on_term)
        if hasattr(signal, "SIGHUP"):
            signal.signal(signal.SIGHUP, on_hup)

    # -------------------------------------------------------------- request

    def _submit(self, req: dict, request_id: Optional[str] = None,
                trace_hop: Optional[int] = None,
                tenant: Optional[str] = None, qos: Optional[str] = None):
        if "tokens" in req:
            ids = [int(t) for t in req["tokens"]]
        else:
            ids = self.tokenizer.encode(str(req.get("prompt", "")).strip())
        return self.engine.submit(
            ids,
            max_new_tokens=int(req.get("max_new_tokens", 32)),
            seed=int(req.get("seed", 0)),
            timeout=float(req["timeout"]) if "timeout" in req else None,
            request_id=request_id,
            prefill_to=(
                str(req["prefill_to"]) if req.get("prefill_to") else None
            ),
            trace_hop=trace_hop,
            tenant=str(tenant or req.get("tenant") or "anon"),
            qos=qos if qos is not None else req.get("qos"),
        )

    @staticmethod
    def _trace_hop_of(handler) -> Optional[int]:
        """The router's propagated hop index (X-Trace-Hop), or None for a
        direct client — a garbled header is a dropped trace attr, never a
        rejected request."""
        raw = handler.headers.get("X-Trace-Hop")
        if raw is None:
            return None
        try:
            return int(raw)
        except (TypeError, ValueError):
            return None

    def _generate(self, handler, req: dict) -> None:
        # inbound correlation id (header wins over body field); the engine
        # generates one at admission when the client sent none — either way
        # every response carries it back as X-Request-Id
        rid_in = handler.headers.get("X-Request-Id") or req.get("request_id")
        try:
            handle = self._submit(
                req, request_id=rid_in,
                trace_hop=self._trace_hop_of(handler),
                # header wins over body field, same precedence as the
                # request id — the router forwards both in the relay body
                tenant=handler.headers.get("X-Tenant-Key"),
                qos=handler.headers.get("X-QoS-Class"),
            )
        except (TypeError, ValueError) as exc:
            # ill-typed field VALUES ({"timeout": "abc"}) are the client's
            # error — 400, not a dropped connection with a server traceback
            handler._json(400, {"error": f"bad request field: {exc}"})
            return
        rid_hdr = {"X-Request-Id": handle.rid}
        if handle.status == REJECTED:
            if handle.retryable:
                # drain / shed / backpressure: honest fast failure the
                # client should retry elsewhere — Retry-After sized by the
                # engine (remaining drain window, or a beat for the queue).
                # Quota exhaustion and brownout suspension are 429s too:
                # the CLIENT is over its allotment, the replica is fine
                err = handle.error or ""
                code = 429 if (
                    "queue full" in err or "quota" in err
                    or "brownout" in err
                ) else 503
                handler._json(
                    code,
                    {"error": handle.error, "status": handle.status,
                     "request_id": handle.rid},
                    headers={
                        "Retry-After": str(
                            max(1, math.ceil(handle.retry_after or 1.0))
                        ),
                        **rid_hdr,
                    },
                )
            else:
                handler._json(400, {"error": handle.error,
                                    "status": handle.status,
                                    "request_id": handle.rid},
                              headers=rid_hdr)
            return
        if handle.status == FAILED:
            # dead engine: an outage must read as 503, never as a 200 with
            # zero tokens
            handler._json(503, {"error": handle.error, "status": handle.status,
                                "request_id": handle.rid}, headers=rid_hdr)
            return
        if not req.get("stream", True):
            tokens = handle.result()
            if handle.status == FAILED:
                # the engine died AFTER admission — same outage as the
                # submit-time check above, same 503 (never a 200 with an
                # empty/truncated body a load balancer reads as healthy)
                handler._json(503, {"error": handle.error,
                                    "status": handle.status,
                                    "request_id": handle.rid}, headers=rid_hdr)
                return
            text = self._full_text(tokens)
            doc = {
                "status": handle.status, "tokens": tokens, "text": text,
                "request_id": handle.rid,
                # per-request cost ledger (PR 15): what this generation
                # actually consumed — the router completes it with
                # fleet-side fields and rolls it up per tenant
                "ledger": handle.ledger_snapshot(),
            }
            if handle.status == MIGRATED:
                # disaggregated handoff: the stream continues at this
                # replica — the router's attach hop picks it up there
                doc["migrated_to"] = handle.migrated_to
            handler._json(200, doc, headers=rid_hdr)
            return
        handler.send_response(200)
        handler.send_header("Content-Type", "text/event-stream")
        handler.send_header("Cache-Control", "no-cache")
        handler.send_header("X-Request-Id", handle.rid)
        handler.end_headers()
        self._stream_events(handler, handle)

    def _stream_events(self, handler, handle) -> None:
        """Pump one handle's token events onto an SSE connection whose
        headers are already sent (shared by /generate streams and /attach
        takeovers of imported streams)."""
        decoder = StreamDecoder(self.tokenizer)
        pieces: list = []
        eos = self.engine.eos_token_id
        # a live SSE consumer is draining the event queue from here on:
        # arm the per-handle emit-buffer bound so a consumer that stops
        # reading (stalled client) retires the stream instead of growing
        # the queue without limit
        handle.consumer_attached = True
        chaos = self.engine._chaos
        events_out = 0
        try:
            # the EOS token is swallowed, not break-ed on: the loop must end
            # on the 'done' event so handle.status is terminal by the time
            # the final SSE event reports it (the engine emits the eos token
            # BEFORE finishing the handle — an early break races that)
            while True:
                event = handle.next_event(timeout=_LIVENESS_POLL_S)
                if event is None:
                    # no token yet (queued, or a slow tick): is the client
                    # still there? A disconnected client must not hold a
                    # queue position — or later a slot — for a generation
                    # nobody will read
                    if _client_gone(handler.connection):
                        handle.cancel()
                        return
                    continue
                kind, token = event
                if kind != "token":
                    break
                events_out += 1
                if chaos is not None:
                    # slow_client fault: THIS consumer stops draining for
                    # ``duration`` seconds mid-stream — the engine keeps
                    # decoding into the bounded emit buffer meanwhile
                    stall = chaos.client_stall_s(events_out)
                    if stall > 0:
                        time.sleep(stall)
                if eos is not None and token == eos:
                    continue
                piece = decoder.push(token)
                if piece is not None:
                    pieces.append(piece)
                    self._event(handler, {"token": token, "text": piece})
                else:
                    # detok buffered the piece (partial UTF-8 across BPE
                    # boundaries): the token id still goes on the wire —
                    # the fleet router's mid-stream failover resumes from
                    # the ids it relayed, and a resume prompt missing
                    # buffered tokens would diverge even under greedy.
                    # text stays PRESENT (empty) so ``e["text"]`` consumers
                    # keep working and joins are unchanged
                    self._event(handler, {"token": token, "text": ""})
            tail = decoder.flush()
            if tail is not None:
                pieces.append(tail)
                self._event(handler, {"text": tail})
            done = {
                "done": True,
                "status": handle.status,
                "text": "".join(pieces),
                "error": handle.error,
                # the fleet router keys failover on this: a retryable
                # failure mid-stream is resumed on another replica
                "retryable": handle.retryable,
                "request_id": handle.rid,
                # per-request cost ledger (PR 15), cumulative across
                # migration hops (it rides the page-span payload)
                "ledger": handle.ledger_snapshot(),
            }
            if handle.status == MIGRATED:
                # zero-recompute handoff: the router attaches at the named
                # replica and the client stream continues seamlessly
                done["migrated_to"] = handle.migrated_to
            self._event(handler, done)
        except (BrokenPipeError, ConnectionResetError):
            # client went away: release the slot instead of decoding into
            # the void
            handle.cancel()

    def _event(self, handler, obj) -> None:
        handler.wfile.write(b"data: " + json.dumps(obj).encode() + b"\n\n")
        handler.wfile.flush()

    def _full_text(self, tokens) -> str:
        eos = self.engine.eos_token_id
        return decode_tokens(self.tokenizer, [t for t in tokens if t != eos])


def run_server(
    engine: ServingEngine,
    tokenizer,
    host: str = "127.0.0.1",
    port: int = 8000,
    background: bool = False,
    reload_source=None,
    drain_deadline_s: Optional[float] = 30.0,
    max_body_bytes: int = 1 << 20,
    admin_token: Optional[str] = None,
) -> Optional[ServingServer]:
    """Start the serving front end. ``background=True`` returns the running
    server (tests); otherwise blocks until SIGTERM (graceful drain, exit 0)
    or interrupt, with SIGHUP hot-reloading from ``reload_source``."""
    server = ServingServer(
        engine, tokenizer, host=host, port=port,
        max_body_bytes=max_body_bytes, reload_source=reload_source,
        admin_token=admin_token,
    )
    if background:
        server.start()
        return server
    server.install_signal_handlers(drain_deadline_s=drain_deadline_s)
    print(
        f"serving on http://{host}:{server.port} "
        f"({engine.n_slots} slots, cache_len {engine.cache_len}) — "
        "POST /generate, GET /healthz, GET /metrics (JSON; Prometheus text "
        "via Accept: text/plain), POST /admin/reload, POST /admin/profile; "
        f"SIGTERM drains ({drain_deadline_s}s deadline), SIGHUP reloads",
        flush=True,
    )
    server.serve_forever()
    return None
