"""Streaming HTTP front end for the continuous-batching engine.

Stdlib-only (``http.server`` threads + SSE) so the serving surface works in
this image without extra dependencies — the reference's only UI was a
CUDA+gradio app (reference ``app.py``). Endpoints:

- ``POST /generate``: JSON body ``{"prompt": str | "tokens": [int],
  "max_new_tokens": int, "seed": int, "timeout": float, "stream": bool}``.
  With ``stream`` (default true) the response is ``text/event-stream``: one
  ``data: {"token": id, "text": piece}`` event per committed text piece and
  a final ``data: {"done": true, "status": ..., "text": full}``. Without, a
  single JSON document. Backpressure maps to HTTP 429 (queue full) / 400
  (invalid request).
- ``GET /healthz``: liveness + occupancy/queue snapshot.
- ``GET /metrics``: the full serving-metrics snapshot (TTFT/ITL percentiles,
  tokens/s, rejects) as JSON.

One scheduler thread drives ``engine.step()``; HTTP handler threads only
``submit()`` and drain per-request queues, so a slow client never stalls
decode for everyone else (the whole point of continuous batching).
"""
from __future__ import annotations

import json
import select
import socket
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from zero_transformer_tpu.serving.detok import StreamDecoder, decode_tokens
from zero_transformer_tpu.serving.engine import FAILED, REJECTED, ServingEngine

# how long an SSE handler blocks on the next token before re-checking that
# the client is still connected (a request parked in the admission queue, or
# a half-open peer that will never RST, produces no write to fail on)
_LIVENESS_POLL_S = 0.5


def _client_gone(conn) -> bool:
    """True when the peer has closed its end: for SSE the client sends
    nothing after the POST body, so a READABLE socket whose peek returns
    b'' is a FIN. Half-open peers (host gone, no FIN/RST) still need the
    write-failure path — this catches the common orderly close."""
    try:
        readable, _, _ = select.select([conn], [], [], 0)
        if readable:
            return conn.recv(1, socket.MSG_PEEK) == b""
    except OSError:
        return True
    return False


class ServingServer:
    """Own the HTTP server + the engine's scheduler thread."""

    def __init__(self, engine: ServingEngine, tokenizer, host: str = "127.0.0.1",
                 port: int = 8000):
        self.engine = engine
        self.tokenizer = tokenizer
        self._stop = threading.Event()
        self._scheduler = threading.Thread(
            target=engine.run, args=(self._stop,), name="serve-scheduler",
            daemon=True,
        )
        outer = self

        class Handler(BaseHTTPRequestHandler):
            # quiet by default; the engine's metrics logger is the log surface
            def log_message(self, fmt, *args):  # noqa: A003
                pass

            def _json(self, code: int, obj) -> None:
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802
                if self.path == "/healthz":
                    # a dead scheduler thread means nothing will ever decode
                    # again — that must not read as "ok" to a load balancer
                    alive = outer._scheduler.is_alive() or not outer._scheduler.ident
                    self._json(200 if alive else 503, {
                        "status": "ok" if alive else "scheduler dead",
                        "slots": outer.engine.n_slots,
                        "active": outer.engine.active_count,
                        "queued": outer.engine.queue_depth,
                    })
                elif self.path == "/metrics":
                    self._json(200, outer.engine.metrics_snapshot())
                else:
                    self._json(404, {"error": f"no route {self.path}"})

            def do_POST(self):  # noqa: N802
                if self.path != "/generate":
                    self._json(404, {"error": f"no route {self.path}"})
                    return
                try:
                    length = int(self.headers.get("Content-Length", 0))
                    req = json.loads(self.rfile.read(length) or b"{}")
                except (ValueError, json.JSONDecodeError):
                    self._json(400, {"error": "malformed JSON body"})
                    return
                if not isinstance(req, dict):
                    # valid JSON but not an object ([1,2], "x") — still the
                    # client's error, not a handler-thread traceback
                    self._json(400, {"error": "body must be a JSON object"})
                    return
                outer._generate(self, req)

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    # ------------------------------------------------------------ lifecycle

    def start(self) -> None:
        self._scheduler.start()
        self._server_thread = threading.Thread(
            target=self._httpd.serve_forever, name="serve-http", daemon=True
        )
        self._server_thread.start()

    def serve_forever(self) -> None:
        self._scheduler.start()
        try:
            self._httpd.serve_forever()
        finally:
            self.stop()

    def stop(self) -> None:
        self._stop.set()
        self._httpd.shutdown()

    # -------------------------------------------------------------- request

    def _submit(self, req: dict):
        if "tokens" in req:
            ids = [int(t) for t in req["tokens"]]
        else:
            ids = self.tokenizer.encode(str(req.get("prompt", "")).strip())
        return self.engine.submit(
            ids,
            max_new_tokens=int(req.get("max_new_tokens", 32)),
            seed=int(req.get("seed", 0)),
            timeout=float(req["timeout"]) if "timeout" in req else None,
        )

    def _generate(self, handler, req: dict) -> None:
        try:
            handle = self._submit(req)
        except (TypeError, ValueError) as exc:
            # ill-typed field VALUES ({"timeout": "abc"}) are the client's
            # error — 400, not a dropped connection with a server traceback
            handler._json(400, {"error": f"bad request field: {exc}"})
            return
        if handle.status == REJECTED:
            code = 429 if "queue full" in (handle.error or "") else 400
            handler._json(code, {"error": handle.error, "status": handle.status})
            return
        if handle.status == FAILED:
            # dead engine: an outage must read as 503, never as a 200 with
            # zero tokens
            handler._json(503, {"error": handle.error, "status": handle.status})
            return
        if not req.get("stream", True):
            tokens = handle.result()
            if handle.status == FAILED:
                # the engine died AFTER admission — same outage as the
                # submit-time check above, same 503 (never a 200 with an
                # empty/truncated body a load balancer reads as healthy)
                handler._json(503, {"error": handle.error, "status": handle.status})
                return
            text = self._full_text(tokens)
            handler._json(200, {
                "status": handle.status, "tokens": tokens, "text": text,
            })
            return
        handler.send_response(200)
        handler.send_header("Content-Type", "text/event-stream")
        handler.send_header("Cache-Control", "no-cache")
        handler.end_headers()
        decoder = StreamDecoder(self.tokenizer)
        pieces: list = []
        eos = self.engine.eos_token_id
        try:
            # the EOS token is swallowed, not break-ed on: the loop must end
            # on the 'done' event so handle.status is terminal by the time
            # the final SSE event reports it (the engine emits the eos token
            # BEFORE finishing the handle — an early break races that)
            while True:
                event = handle.next_event(timeout=_LIVENESS_POLL_S)
                if event is None:
                    # no token yet (queued, or a slow tick): is the client
                    # still there? A disconnected client must not hold a
                    # queue position — or later a slot — for a generation
                    # nobody will read
                    if _client_gone(handler.connection):
                        handle.cancel()
                        return
                    continue
                kind, token = event
                if kind != "token":
                    break
                if eos is not None and token == eos:
                    continue
                piece = decoder.push(token)
                if piece is not None:
                    pieces.append(piece)
                    self._event(handler, {"token": token, "text": piece})
            tail = decoder.flush()
            if tail is not None:
                pieces.append(tail)
                self._event(handler, {"text": tail})
            self._event(handler, {
                "done": True,
                "status": handle.status,
                "text": "".join(pieces),
                "error": handle.error,
            })
        except (BrokenPipeError, ConnectionResetError):
            # client went away: release the slot instead of decoding into
            # the void
            handle.cancel()

    def _event(self, handler, obj) -> None:
        handler.wfile.write(b"data: " + json.dumps(obj).encode() + b"\n\n")
        handler.wfile.flush()

    def _full_text(self, tokens) -> str:
        eos = self.engine.eos_token_id
        return decode_tokens(self.tokenizer, [t for t in tokens if t != eos])


def run_server(
    engine: ServingEngine,
    tokenizer,
    host: str = "127.0.0.1",
    port: int = 8000,
    background: bool = False,
) -> Optional[ServingServer]:
    """Start the serving front end. ``background=True`` returns the running
    server (tests); otherwise blocks until interrupted."""
    server = ServingServer(engine, tokenizer, host=host, port=port)
    if background:
        server.start()
        return server
    print(
        f"serving on http://{host}:{server.port} "
        f"({engine.n_slots} slots, cache_len {engine.cache_len}) — "
        "POST /generate, GET /healthz, GET /metrics",
        flush=True,
    )
    server.serve_forever()
    return None
