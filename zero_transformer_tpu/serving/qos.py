"""Overload isolation: QoS classes, token-bucket quotas, weighted-fair
admission, and brownout degradation (the ISSUE-18 plane).

The serving stack already survives process death, migration, and corrupt
artifacts; this module makes it survive *other tenants*. Four primitives,
all host-side and stdlib-only, shared by the engine (per-replica admission)
and the router (fleet-wide policy):

- ``QosPolicy`` / ``QosClassConfig``: the declared classes (``gold`` >
  ``standard`` > ``batch``), each with a DWRR weight, slot/page
  reservation floors, per-tenant token-bucket parameters, and a
  class-aware ``Retry-After``. Declared once in
  ``configs/slo_default.json`` next to the per-class SLO objectives; the
  committed code defaults are deliberately inert (no floors, effectively
  unlimited buckets) so a policy-less engine behaves exactly as before.
- ``TokenBucket`` / ``TenantBuckets``: per-(tenant, class) admission
  quotas priced in *tokens of work* (prompt + max_new_tokens), so a
  flooding tenant exhausts its own bucket instead of everyone's p99. A
  failed ``take`` returns the honest Retry-After (seconds until the
  bucket refills to the request's cost).
- ``ClassQueue``: the admission queue as per-class deficit-weighted
  round-robin. Exact DWRR without spinning: each pop computes, per
  nonempty class, how many quantum rounds its head needs, advances every
  contending class by that many rounds, and serves the winner — served
  work-rate converges to the weight ratio while FIFO order holds within
  a class. Floors enter as an ``eligible`` predicate: a class whose
  admission would eat a higher class's reserved slot/pages simply does
  not contend this round (and accrues no deficit for it).
- ``BrownoutController``: the fleet-wide degradation ladder
  (``normal -> no_spec -> shrink_batch -> suspend_batch``) with
  hysteresis — escalate one rung per hot evaluation, de-escalate one
  rung only after ``calm_evals`` consecutive calm ones, so rungs fully
  revert when load subsides instead of flapping.
"""
from __future__ import annotations

import dataclasses
import math
import threading
from collections import OrderedDict, deque
from typing import (
    Any, Callable, Dict, Iterable, Iterator, List, Optional, Tuple,
)

# Rank order IS priority order: index 0 is the most protected class.
QOS_CLASSES = ("gold", "standard", "batch")
DEFAULT_CLASS = "standard"

# Degradation ladder, mildest first. Every rung includes the effects of
# the rungs before it (suspend_batch implies shrunk budgets and no
# speculation).
BROWNOUT_RUNGS = ("normal", "no_spec", "shrink_batch", "suspend_batch")


@dataclasses.dataclass(frozen=True)
class QosClassConfig:
    """One declared QoS class.

    weight: DWRR weight — relative share of admission work-rate under
      contention (gold 8 : standard 4 : batch 1 by default).
    slot_floor: decode slots held back for this class: a lower class may
      not take a slot while doing so would leave fewer free slots than
      this class's unmet floor.
    page_floor_frac: same reservation for the paged-KV pool, as a
      fraction of total pool pages.
    rate / burst: per-tenant token-bucket refill (work-tokens/s) and
      capacity. The committed defaults are effectively unlimited — quotas
      bind only where a config declares finite ones.
    retry_after_s: the class-aware Retry-After floor for quota/brownout
      rejections (batch waits longer than gold by design).
    brownout_max_new_tokens: the shrunken per-request budget this class
      gets at the ``shrink_batch`` rung and above (None = never shrunk).
    """

    name: str
    weight: float = 1.0
    slot_floor: int = 0
    page_floor_frac: float = 0.0
    rate: float = float("inf")
    burst: float = float("inf")
    retry_after_s: float = 1.0
    brownout_max_new_tokens: Optional[int] = None

    def __post_init__(self):
        if self.weight <= 0:
            raise ValueError(f"qos class {self.name!r}: weight must be > 0")
        if self.slot_floor < 0 or not (0.0 <= self.page_floor_frac <= 1.0):
            raise ValueError(
                f"qos class {self.name!r}: floors must be >= 0 "
                f"(page_floor_frac in [0, 1])"
            )
        if self.rate <= 0 or self.burst <= 0:
            raise ValueError(
                f"qos class {self.name!r}: rate and burst must be > 0"
            )


_DEFAULT_CLASSES: Tuple[QosClassConfig, ...] = (
    QosClassConfig(name="gold", weight=8.0, retry_after_s=0.5),
    QosClassConfig(name="standard", weight=4.0, retry_after_s=1.0),
    QosClassConfig(name="batch", weight=1.0, retry_after_s=5.0,
                   brownout_max_new_tokens=16),
)


class QosPolicy:
    """The declared class set plus lookup helpers. Unknown or missing
    class names resolve to ``default_class`` — a client typo degrades to
    standard treatment, never to a 500."""

    def __init__(
        self,
        classes: Optional[Iterable[QosClassConfig]] = None,
        default_class: str = DEFAULT_CLASS,
    ):
        classes = tuple(classes) if classes is not None else _DEFAULT_CLASSES
        names = [c.name for c in classes]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate qos class names: {names}")
        if default_class not in names:
            raise ValueError(
                f"default_class {default_class!r} not in classes {names}"
            )
        self.classes: "OrderedDict[str, QosClassConfig]" = OrderedDict(
            (c.name, c) for c in classes
        )
        self.default_class = default_class
        self._rank = {name: i for i, name in enumerate(self.classes)}

    @classmethod
    def from_config(cls, spec: Optional[Dict[str, Any]]) -> "QosPolicy":
        """Policy from the ``qos`` block of ``configs/slo_default.json``:
        ``{"default_class": ..., "classes": {name: {weight: ...}}}``.
        Unknown keys fail loudly (a typo'd knob must not silently weaken
        isolation). ``None``/empty -> the inert committed defaults."""
        if not spec:
            return cls()
        if not isinstance(spec, dict):
            raise ValueError(f"qos config must be a dict, got {type(spec)}")
        unknown = set(spec) - {"default_class", "classes"}
        if unknown:
            raise ValueError(f"qos config: unknown keys {sorted(unknown)}")
        allowed = {f.name for f in dataclasses.fields(QosClassConfig)}
        defaults = {c.name: c for c in _DEFAULT_CLASSES}
        out: List[QosClassConfig] = []
        for name, raw in (spec.get("classes") or {}).items():
            bad = set(raw) - (allowed - {"name"})
            if bad:
                raise ValueError(
                    f"qos class {name!r}: unknown keys {sorted(bad)} "
                    f"(allowed: {sorted(allowed - {'name'})})"
                )
            base = defaults.get(name)
            merged = dict(dataclasses.asdict(base)) if base else {}
            merged.update(raw)
            merged["name"] = name
            out.append(QosClassConfig(**merged))
        # classes the config omits keep their committed defaults, in rank
        # order, so a partial config never drops a class from the ladder
        declared = {c.name for c in out}
        for c in _DEFAULT_CLASSES:
            if c.name not in declared:
                out.append(c)
        out.sort(key=lambda c: (
            QOS_CLASSES.index(c.name) if c.name in QOS_CLASSES else len(
                QOS_CLASSES)
        ))
        return cls(out, default_class=spec.get("default_class", DEFAULT_CLASS))

    def normalize(self, name: Optional[str]) -> str:
        name = str(name or "").strip().lower()
        return name if name in self.classes else self.default_class

    def class_of(self, name: Optional[str]) -> QosClassConfig:
        return self.classes[self.normalize(name)]

    def rank(self, name: Optional[str]) -> int:
        """0 = most protected. Lower rank preempts / outranks higher."""
        return self._rank[self.normalize(name)]

    def names(self) -> Tuple[str, ...]:
        return tuple(self.classes)

    def snapshot(self) -> Dict[str, Dict[str, Any]]:
        return {
            name: dataclasses.asdict(cfg)
            for name, cfg in self.classes.items()
        }


# ------------------------------------------------------------- token buckets


class TokenBucket:
    """Work-token bucket (not thread-safe; owners lock around it).

    ``take(cost, now)`` returns 0.0 on success (cost deducted) or the
    seconds until the bucket will hold ``cost`` — the honest Retry-After.
    ``scale`` multiplies rate and burst at take-time: the router scales a
    tenant's fleet bucket by the number of routable replicas, so fleet
    capacity and fleet quota move together."""

    def __init__(self, rate: float, burst: float):
        self.rate = float(rate)
        self.burst = float(burst)
        self.level = float(burst)
        self._last: Optional[float] = None

    def take(self, cost: float, now: float, scale: float = 1.0) -> float:
        rate = self.rate * max(1e-9, scale)
        burst = self.burst * max(1e-9, scale)
        if self._last is None:
            # first take: start full AT THE CURRENT SCALE (the router's
            # fleet bucket opens with the whole fleet's burst, not one
            # replica's worth)
            self._last = now
            self.level = burst
        if math.isinf(burst):
            return 0.0
        self.level = min(burst, self.level + rate * max(0.0, now - self._last))
        self._last = now
        if cost <= self.level:
            self.level -= cost
            return 0.0
        if rate <= 0 or not math.isfinite(rate):
            return 1.0
        return (cost - self.level) / rate


class TenantBuckets:
    """Bounded LRU of per-(tenant, class) ``TokenBucket``s. Thread-safe.
    LRU-bounded for the same reason as ``TenantLedger``: a tenant-id
    cardinality attack must not balloon the host."""

    def __init__(self, policy: QosPolicy, capacity: int = 4096):
        self.policy = policy
        self.capacity = max(1, int(capacity))
        self._buckets: "OrderedDict[Tuple[str, str], TokenBucket]" = (
            OrderedDict()
        )
        self._lock = threading.Lock()

    def take(
        self, tenant: str, qos: Optional[str], cost: float, now: float,
        scale: float = 1.0,
    ) -> float:
        """0.0 = admitted (cost charged); > 0 = Retry-After seconds."""
        cls = self.policy.class_of(qos)
        key = (str(tenant or "anon")[:64], cls.name)
        with self._lock:
            bucket = self._buckets.get(key)
            if bucket is None:
                if len(self._buckets) >= self.capacity:
                    self._buckets.popitem(last=False)
                bucket = self._buckets[key] = TokenBucket(
                    cls.rate, cls.burst
                )
            self._buckets.move_to_end(key)
            wait = bucket.take(cost, now, scale=scale)
        return max(wait, cls.retry_after_s) if wait > 0 else 0.0

    def __len__(self) -> int:
        with self._lock:
            return len(self._buckets)


# ------------------------------------------------------- DWRR admission queue


class ClassQueue:
    """Per-class deficit-weighted-round-robin admission queue.

    Deque-compatible where the engine needs it (``len``, ``bool``,
    iteration in rank-then-FIFO order, ``append``/``appendleft``,
    ``clear``, ``rebuild``) so the sweep/drain/abort paths keep their
    shape. ``popleft(eligible=...)`` is the fair pop; ``cost`` prices a
    waiting request in work-tokens (default 1 per request)."""

    def __init__(
        self,
        policy: Optional[QosPolicy] = None,
        cost: Optional[Callable[[Any], float]] = None,
        class_of: Optional[Callable[[Any], str]] = None,
        quantum: float = 1.0,
    ):
        self.policy = policy or QosPolicy()
        self._cost = cost or (lambda h: 1.0)
        self._class_of = class_of or (
            lambda h: getattr(getattr(h, "request", h), "qos", None)
        )
        self.quantum = float(quantum)
        self._q: Dict[str, deque] = {
            name: deque() for name in self.policy.names()
        }
        self._deficit: Dict[str, float] = {
            name: 0.0 for name in self.policy.names()
        }

    def _cls(self, handle: Any) -> str:
        return self.policy.normalize(self._class_of(handle))

    def __len__(self) -> int:
        return sum(len(q) for q in self._q.values())

    def __bool__(self) -> bool:
        return any(self._q.values())

    def __iter__(self) -> Iterator[Any]:
        for name in self.policy.names():
            yield from self._q[name]

    def counts(self) -> Dict[str, int]:
        return {name: len(q) for name, q in self._q.items()}

    def append(self, handle: Any) -> None:
        self._q[self._cls(handle)].append(handle)

    def appendleft(self, handle: Any) -> None:
        """Push back a popped-but-unadmittable head, refunding its DWRR
        charge so a paged-admission miss does not count against the
        class's fair share."""
        cls = self._cls(handle)
        self._q[cls].appendleft(handle)
        self._deficit[cls] += max(1.0, float(self._cost(handle)))

    def refund(self, handle: Any) -> None:
        """Refund a pop that admitted nothing (cancelled/expired head)."""
        cls = self._cls(handle)
        self._deficit[cls] += max(1.0, float(self._cost(handle)))

    def clear(self) -> None:
        for q in self._q.values():
            q.clear()
        for name in self._deficit:
            self._deficit[name] = 0.0

    def rebuild(self, handles: Iterable[Any]) -> None:
        """Replace contents (the sweep path), preserving arrival order
        within each class; deficits persist so a sweep is not a fairness
        reset."""
        for q in self._q.values():
            q.clear()
        for handle in handles:
            self.append(handle)

    def popleft(
        self, eligible: Optional[Callable[[str], bool]] = None,
    ) -> Optional[Any]:
        """Fair pop. Exact DWRR, O(classes): compute how many quantum
        rounds each contending head needs, advance every contender by the
        winning round count, serve the winner and charge its cost.
        ``eligible(class_name)`` gates contention (reservation floors) —
        an ineligible class neither serves nor accrues deficit."""
        contenders: List[Tuple[float, int, str, float]] = []
        for name in self.policy.names():
            q = self._q[name]
            if not q:
                # standard DWRR: an empty class forfeits its deficit, so
                # idle classes cannot bank unbounded credit
                self._deficit[name] = 0.0
                continue
            if eligible is not None and not eligible(name):
                continue
            cost = max(1.0, float(self._cost(q[0])))
            inc = self.quantum * self.policy.classes[name].weight
            need = max(0.0, cost - self._deficit[name])
            rounds = math.ceil(need / inc) if need > 0 else 0
            contenders.append((rounds, self.policy.rank(name), name, cost))
        if not contenders:
            return None
        contenders.sort()
        rounds, _, winner, cost = contenders[0]
        if rounds:
            for _, _, name, _ in contenders:
                self._deficit[name] += (
                    rounds * self.quantum * self.policy.classes[name].weight
                )
        self._deficit[winner] -= cost
        handle = self._q[winner].popleft()
        if not self._q[winner]:
            self._deficit[winner] = 0.0
        return handle

    def pop_lowest_class(self, above_rank: int = 0) -> Optional[Any]:
        """Shed candidate: the most recently queued request of the lowest
        class whose rank is strictly greater than ``above_rank`` (queue-
        full pressure evicts the newest batch request first, never a
        higher class)."""
        for name in reversed(self.policy.names()):
            if self.policy.rank(name) <= above_rank:
                continue
            if self._q[name]:
                return self._q[name].pop()
        return None

    def best_waiting_rank(self) -> Optional[int]:
        for name in self.policy.names():
            if self._q[name]:
                return self.policy.rank(name)
        return None


# ------------------------------------------------------ reservation floors


def reserved_above(
    policy: QosPolicy,
    cls: str,
    floors: Dict[str, float],
    in_use: Dict[str, float],
) -> float:
    """Capacity held back from class ``cls``: the unmet reservation floors
    of every strictly higher class. A higher class already using its
    floor releases that much back to the pool."""
    rank = policy.rank(cls)
    held = 0.0
    for name in policy.names():
        if policy.rank(name) >= rank:
            continue
        held += max(0.0, floors.get(name, 0.0) - in_use.get(name, 0.0))
    return held


# --------------------------------------------------------------- brownout


class BrownoutController:
    """The degradation ladder with hysteresis. ``observe(hot)`` once per
    SLO evaluation: a hot evaluation (a protected class is burning)
    escalates one rung; ``calm_evals`` consecutive calm evaluations
    de-escalate one rung — so a sustained calm spell walks the ladder all
    the way back to ``normal`` (full revert), while a single calm blip
    mid-overload changes nothing. Thread-safe."""

    def __init__(
        self,
        rungs: Tuple[str, ...] = BROWNOUT_RUNGS,
        calm_evals: int = 3,
    ):
        if len(rungs) < 2:
            raise ValueError("brownout needs at least 2 rungs")
        self.rungs = tuple(rungs)
        self.calm_evals = max(1, int(calm_evals))
        self._idx = 0
        self._calm = 0
        self._lock = threading.Lock()

    @property
    def rung(self) -> str:
        return self.rungs[self._idx]

    @property
    def rung_index(self) -> int:
        return self._idx

    def observe(self, hot: bool) -> Optional[Tuple[str, str]]:
        """Returns ``(old_rung, new_rung)`` on a transition, else None."""
        with self._lock:
            old = self._idx
            if hot:
                self._calm = 0
                if self._idx < len(self.rungs) - 1:
                    self._idx += 1
            else:
                self._calm += 1
                if self._calm >= self.calm_evals and self._idx > 0:
                    self._idx -= 1
                    self._calm = 0
            if self._idx != old:
                return (self.rungs[old], self.rungs[self._idx])
            return None

    def force(self, rung: str) -> Optional[Tuple[str, str]]:
        """Operator override (``POST /admin/brownout`` on the router)."""
        if rung not in self.rungs:
            raise ValueError(
                f"unknown brownout rung {rung!r} (rungs: {self.rungs})"
            )
        with self._lock:
            old = self.rungs[self._idx]
            self._idx = self.rungs.index(rung)
            self._calm = 0
            return (old, rung) if old != rung else None

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "rung": self.rungs[self._idx],
                "rung_index": self._idx,
                "rungs": list(self.rungs),
                "calm_streak": self._calm,
                "calm_evals": self.calm_evals,
            }


def rung_at_least(rung: str, floor: str) -> bool:
    """True when ``rung`` is at or beyond ``floor`` on the default ladder
    (unknown rungs compare as ``normal``)."""
    order = {name: i for i, name in enumerate(BROWNOUT_RUNGS)}
    return order.get(rung, 0) >= order.get(floor, 0)
