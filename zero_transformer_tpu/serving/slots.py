"""Slot-based KV-cache manager for continuous batching.

One fixed ``[n_slots, cache_len]`` decode cache (allocated through
``inference.init_cache`` — int8-KV aware, optionally tensor-sharded) whose
rows are SLOTS: a request is prefetched into a fresh single-row cache, then
copied into a free slot with ``lax.dynamic_update_slice``; from then on every
scheduler tick runs ONE fused decode step over all slots. The piece that
makes rows independent is the cache index: ``init_cache`` gives the scalar
``cache_index``/``decode_pos`` the single-request paths use, and
``vectorize_index`` widens it to a per-slot ``[n_slots]`` vector — the
model's decode path (``models.gpt.Attention``) sees a vector index and
switches every position-dependent computation (writes, validity mask, RoPE /
ALiBi / causal biases) to per-row form.

Jit-signature stability invariant: every device function here is traced for
ONE shape — the full ``[n_slots, ...]`` cache with dynamic slot/length
scalars — so admissions, retirements, and occupancy changes never recompile.
"""
from __future__ import annotations

import functools
import json
import struct
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from zero_transformer_tpu.inference.generate import init_cache

# cache leaves that hold POSITIONS, not K/V data; widened per-slot.
# (cache_index: per-layer attention write position; decode_pos: the learned-
# position table offset at the Transformer level.)
INDEX_LEAVES = ("cache_index", "decode_pos")

# K/V byte-holding leaves of the PAGED cache ([n_pages, page, ...] pools);
# the int32 per-row page map is its own leaf
POOL_LEAVES = ("cached_key", "cached_value", "key_scale", "value_scale")
TABLE_LEAF = "block_table"


def _leaf_name(path) -> str:
    last = path[-1]
    return str(last.key if hasattr(last, "key") else last)


def _cache_struct(model, batch: int):
    """Shape-only cache structure for a [batch, ...] run (no materialization)."""
    from zero_transformer_tpu.utils.jax_compat import clear_abstract_mesh

    with clear_abstract_mesh():
        return jax.eval_shape(
            lambda r: model.init(r, jnp.zeros((batch, 1), jnp.int32)),
            jax.random.PRNGKey(0),
        )["cache"]


def vectorize_index(cache: Any, n_slots: int) -> Any:
    """Widen scalar index leaves to per-slot vectors: shape ``s`` -> ``s + (n_slots,)``
    int32 zeros. K/V leaves pass through untouched (same buffers)."""

    def widen(path, leaf):
        if _leaf_name(path) in INDEX_LEAVES:
            return jnp.zeros(leaf.shape + (n_slots,), jnp.int32)
        return leaf

    return jax.tree_util.tree_map_with_path(widen, cache)


# ---- token-span ops (chunked prefill + prefix cache) -----------------------
#
# Every K/V leaf (and int8 scale leaf) is laid out [..., n_slots, cache_len,
# ...]: the sequence axis sits immediately after the slot axis in every
# layout this repo produces (per-layer [B, L, KVH, D], scanned
# [n_layers, B, L, KVH, D], scales [..., KVH, 1]) — asserted at SlotKVCache
# construction so a future layout change fails loudly instead of silently
# copying the wrong axis. ``axes_items`` (the per-leaf slot-axis map as a
# sorted tuple) is a STATIC argument: one compiled program per cache
# structure, shared across engines, with slot/start as dynamic scalars.


@functools.partial(jax.jit, static_argnums=(0, 1, 2))
def _extract_spans_impl(axes_items, length, count, cache, slot):
    """Copy ``count`` consecutive ``length``-position spans of one slot's
    K/V rows out of the cache in ONE dispatch: a list of
    {leaf path -> [..., 1, length, ...]} trees, span ``j`` covering
    positions ``[j*length, (j+1)*length)``. Batching the spans matters:
    per-span dispatches put the prefix-cache STORE cost (paid by every
    cold shared-prefix request at completion) on the tick thread's
    critical path once per chunk instead of once per request."""
    axes = dict(axes_items)
    spans: list = [{} for _ in range(count)]

    def grab(path, leaf):
        key = jax.tree_util.keystr(path)
        ax = axes.get(key)
        if ax is None or _leaf_name(path) in INDEX_LEAVES:
            return
        for j in range(count):
            starts = [0] * leaf.ndim
            starts[ax], starts[ax + 1] = slot, j * length
            sizes = list(leaf.shape)
            sizes[ax], sizes[ax + 1] = 1, length
            spans[j][key] = jax.lax.dynamic_slice(
                leaf, tuple(starts), tuple(sizes)
            )

    jax.tree_util.tree_map_with_path(grab, cache)
    return spans


@functools.partial(jax.jit, static_argnums=(0,))
def _write_spans_impl(axes_items, cache, spans, slot):
    """Write extracted spans back into one slot's rows, span ``j`` at its
    chunk-aligned position, all in ONE dispatch (the prefix-cache HIT
    path). Index leaves are untouched — the prefill scheduler owns the
    fill cursor; a span copy only moves K/V bytes."""
    axes = dict(axes_items)

    def put(path, leaf):
        key = jax.tree_util.keystr(path)
        if not spans or key not in spans[0]:
            return leaf
        ax = axes[key]
        length = spans[0][key].shape[ax + 1]
        for j, span in enumerate(spans):
            starts = [0] * leaf.ndim
            starts[ax], starts[ax + 1] = slot, j * length
            leaf = jax.lax.dynamic_update_slice(
                leaf, span[key].astype(leaf.dtype), tuple(starts)
            )
        return leaf

    return jax.tree_util.tree_map_with_path(put, cache)


@jax.jit
def _reset_index(cache: Any, keep: jax.Array) -> Any:
    """Zero the positions of retired slots (``keep`` [n_slots] bool). K/V
    rows are left in place — the validity mask (positions < index) already
    excludes them, and the next insert overwrites the row."""

    def reset(path, leaf):
        if _leaf_name(path) in INDEX_LEAVES:
            return jnp.where(keep, leaf, 0)  # keep broadcasts from the right
        return leaf

    return jax.tree_util.tree_map_with_path(reset, cache)


class SlotKVCache:
    """Owns the engine's fixed-shape cache + host-side slot bookkeeping.

    Device state: ``self.cache`` (the [n_slots, cache_len] tree, vector
    index). Host state: which slots are free. The manager is not thread-safe
    by itself — the engine serializes access from its scheduler loop.
    """

    def __init__(self, model, n_slots: int, mesh=None):
        if n_slots < 1:
            raise ValueError("n_slots must be >= 1")
        self.model = model
        self.n_slots = n_slots
        self.mesh = mesh
        self.cache = vectorize_index(
            init_cache(model, n_slots, mesh=mesh), n_slots
        )
        self._free: List[int] = list(range(n_slots))
        self._axes = self._find_batch_axes(model)
        self._insert = self._build_insert()
        # span ops assume [slot, seq] adjacency on every per-position leaf
        # (see _extract_span_impl); verify against the real cache once here
        cap = model.cache_len or model.cfg.max_seq_len
        self.seq_capacity = cap
        for path, leaf in jax.tree_util.tree_leaves_with_path(self.cache):
            ax = self._axes.get(jax.tree_util.keystr(path))
            if ax is not None and (
                leaf.shape[ax] != n_slots or leaf.shape[ax + 1] != cap
            ):
                raise AssertionError(
                    f"cache leaf {jax.tree_util.keystr(path)} breaks the "
                    f"[slots, cache_len] adjacency span ops rely on: shape "
                    f"{leaf.shape}, slot axis {ax}"
                )

    @property
    def axes_items(self) -> Tuple:
        """Per-leaf slot-axis map as a hashable (static-arg) tuple."""
        return tuple(sorted(self._axes.items()))

    # ---- token-span ops --------------------------------------------------

    def _quantized_count(self, length: int, count: int) -> int:
        """Span counts are STATIC in the compiled span ops, so every
        distinct count is a whole compiled program traversing the cache
        tree — an unbounded family under diverse prompt lengths (the same
        storm the engine's prefill-bucket cap exists for). Quantize to the
        next power of two (capped at capacity), bounding the family at
        ~log2(capacity / chunk) programs per direction."""
        cap = max(1, self.seq_capacity // length)
        b = 1
        while b < count:
            b *= 2
        return min(b, cap)

    def extract_spans(self, slot: int, length: int, count: int) -> List[Any]:
        """Copy the first ``count`` consecutive ``length``-position spans of
        ``slot`` in one dispatch (prefix-cache store). Extraction is padded
        to the quantized count; the extra spans are sliced off host-side."""
        padded = self._quantized_count(length, count)
        spans = _extract_spans_impl(
            self.axes_items, length, padded, self.cache, jnp.int32(slot)
        )
        return spans[:count]

    def write_spans(self, spans: List[Any], slot: int) -> None:
        """Write extracted spans into ``slot`` at their chunk-aligned
        positions, one dispatch (prefix-cache hit). The fill cursor stays
        with the caller. Padding spans (the quantized tail, repeats of the
        first span) land at positions >= the caller's fill cursor: the
        validity mask hides everything at or past the cursor, and the
        chunk prefill / decode writes overwrite those positions with real
        K/V before the cursor ever reaches them."""
        if not spans:
            return
        key, leaf = next(iter(spans[0].items()))
        length = leaf.shape[self._axes[key] + 1]
        padded = self._quantized_count(length, len(spans))
        full = list(spans) + [spans[0]] * (padded - len(spans))
        self.cache = _write_spans_impl(
            self.axes_items, self.cache, full, jnp.int32(slot)
        )

    @staticmethod
    def _find_batch_axes(model) -> Dict[str, int]:
        """Per-leaf batch-axis index, found by diffing the cache structure
        for batch=1 vs batch=2 — shape-sniffing a single structure would
        misread layouts where the slot count collides with another dim
        (n_layers == n_slots under the scanned stack). Index leaves don't
        scale with batch (scalar per layer) and get no entry — insert
        handles them by name."""
        one = jax.tree_util.tree_leaves_with_path(_cache_struct(model, 1))
        two = jax.tree_util.tree_leaves_with_path(_cache_struct(model, 2))
        axes: Dict[str, int] = {}
        for (path, a), (path2, b) in zip(one, two):
            assert path == path2, "cache structure must not depend on batch"
            diff = [i for i, (x, y) in enumerate(zip(a.shape, b.shape)) if x != y]
            if diff:
                axes[jax.tree_util.keystr(path)] = diff[0]
        return axes

    def _build_insert(self):
        axes = self._axes

        @jax.jit
        def insert(big, small, slot, true_len):
            def upd(path, b, s):
                if _leaf_name(path) in INDEX_LEAVES:
                    # set [..., slot] = true_len
                    block = jnp.full(b.shape[:-1] + (1,), true_len, b.dtype)
                    starts = (0,) * (b.ndim - 1) + (slot,)
                    return jax.lax.dynamic_update_slice(b, block, starts)
                ax = axes.get(jax.tree_util.keystr(path))
                if ax is None:
                    # leaf does not scale with batch and is not an index —
                    # shared state; keep the engine's copy
                    return b
                starts = [0] * b.ndim
                starts[ax] = slot
                return jax.lax.dynamic_update_slice(
                    b, s.astype(b.dtype), tuple(starts)
                )

            return jax.tree_util.tree_map_with_path(upd, big, small)

        return insert

    # ---- slot bookkeeping ------------------------------------------------

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def active_count(self) -> int:
        return self.n_slots - len(self._free)

    def acquire(self) -> Optional[int]:
        """Claim a free slot index, or None when fully occupied."""
        return self._free.pop(0) if self._free else None

    def insert(self, small_cache: Any, slot: int, true_len: int) -> None:
        """Copy a prefilled single-row cache into ``slot`` and set its
        position to ``true_len`` (the PROMPT length, not the padded prefill
        length — decode overwrites any padded tail progressively)."""
        self.cache = self._insert(
            self.cache, small_cache, jnp.int32(slot), jnp.int32(true_len)
        )

    def release(self, slots: List[int]) -> None:
        """Retire slots: free them and zero their positions so a parked row
        never walks its index toward the capacity poison guard."""
        if not slots:
            return
        for s in slots:
            if s in self._free:
                raise ValueError(f"slot {s} double-released")
            self._free.append(s)
        keep = jnp.asarray(
            [s not in self._free for s in range(self.n_slots)], jnp.bool_
        )
        self.cache = _reset_index(self.cache, keep)


# ---- paged KV cache (block tables over a global page pool) -----------------
#
# The slab above reserves n_slots * cache_len positions of K/V whatever the
# actual sequence lengths; the paged layout below reserves only the pages a
# sequence really fills (PagedAttention, Kwon et al. 2309.06180). Pages are
# REFCOUNTED: a slot mapping a page holds one reference and the paged prefix
# index holds another per cached chunk, so a prefix hit is a refcount bump
# into the new slot's block table — zero K/V bytes move — and nothing frees
# a page while any live slot or cached prefix still maps it.


# ---- transferable page spans (disaggregated prefill/decode + migration) ----
#
# A page span is the HOST-side image of one slot's leading pages: the raw
# K/V bytes (int8 scale leaves included) of every pool leaf plus the
# block-table fragment's geometry. It is the unit that moves between
# replicas — a prefill replica ships finished spans to a decode replica,
# and live migration ships a mid-stream slot's span to its new home. The
# gather/scatter programs are compiled per QUANTIZED page count (power of
# two, same discipline as the span ops above) so diverse sequence lengths
# cannot compile-storm a long-lived replica; padding routes through the
# trash page (gather pads are sliced off host-side, scatter pads write
# garbage into page 0, which nothing ever reads).

_WIRE_MAGIC = b"ZTPG1"


def _dtype_token(dt) -> str:
    """Wire token for a numpy dtype. Extension dtypes (bfloat16, fp8s —
    numpy kind 'V') stringify to an OPAQUE void ('|V2') that the receiver
    cannot reconstruct; ship their NAME instead."""
    dt = np.dtype(dt)
    return dt.name if dt.kind == "V" else dt.str


def _dtype_from_token(token: str):
    try:
        return np.dtype(token)
    except TypeError:
        pass
    # extension dtype by name (bfloat16 etc.) — ml_dtypes ships with jax,
    # so this resolves wherever the pools themselves can exist. An unknown
    # token must surface as ValueError (the wire contract: torn/foreign
    # blobs become a clean 400, never a handler traceback).
    import ml_dtypes

    try:
        return np.dtype(getattr(ml_dtypes, str(token)))
    except (AttributeError, TypeError) as exc:
        raise ValueError(f"unknown dtype token {token!r}") from exc


@jax.jit
def _gather_pages_impl(cache, page_ids):
    """Pull pool pages out of every K/V pool leaf in ONE dispatch:
    {leaf path -> [len(page_ids), ...per-page]} with the page axis moved
    to the front so row ``i`` is page ``page_ids[i]`` whatever the pool
    layout (per-layer [n_pages, page, KVH, D] or scanned
    [L, n_pages, ...]). The compile family is keyed by ``page_ids``'s
    (quantized) length — the caller pads to a power of two."""
    out: Dict[str, jax.Array] = {}

    def grab(path, leaf):
        if _leaf_name(path) not in POOL_LEAVES:
            return
        ax = leaf.ndim - 4
        v = jnp.moveaxis(leaf, ax, 0)
        out[jax.tree_util.keystr(path)] = jnp.take(v, page_ids, axis=0)

    jax.tree_util.tree_map_with_path(grab, cache)
    return out


@functools.partial(jax.jit, donate_argnums=(0,))
def _scatter_pages_impl(cache, page_ids, spans):
    """Inverse of ``_gather_pages_impl``: write span rows into the pool
    pages named by ``page_ids``, one dispatch across every pool leaf.
    Padding rows target the trash page (id 0) — harmless by design."""

    def put(path, leaf):
        key = jax.tree_util.keystr(path)
        if key not in spans:
            return leaf
        ax = leaf.ndim - 4
        v = jnp.moveaxis(leaf, ax, 0)
        v = v.at[page_ids].set(spans[key].astype(v.dtype))
        return jnp.moveaxis(v, 0, ax)

    return jax.tree_util.tree_map_with_path(put, cache)


@jax.jit
def _set_index_slot(cache: Any, slot: jax.Array, value: jax.Array) -> Any:
    """Set ONE slot's fill cursor in every index leaf (migration import:
    the destination's cursor is host-known — prompt + emitted — and the
    imported pages already hold the K/V at [0, cursor))."""

    def upd(path, leaf):
        if _leaf_name(path) not in INDEX_LEAVES:
            return leaf
        block = jnp.full(leaf.shape[:-1] + (1,), value, leaf.dtype)
        starts = (0,) * (leaf.ndim - 1) + (slot,)
        return jax.lax.dynamic_update_slice(leaf, block, starts)

    return jax.tree_util.tree_map_with_path(upd, cache)


def page_span_to_wire(payload: Dict[str, Any]) -> bytes:
    """Serialize a page-span payload (and any JSON-safe extras riding in
    it) to one self-describing byte string: magic + length-prefixed JSON
    header + the leaf buffers concatenated raw. No base64 inflation, no
    pickle — the format is readable by any stdlib-only peer."""
    leaves = payload.get("leaves", {})
    header = {
        k: v for k, v in payload.items() if k != "leaves"
    }
    header["leaves"] = []
    buffers: List[bytes] = []
    for key in sorted(leaves):
        arr = np.ascontiguousarray(leaves[key])
        header["leaves"].append({
            "key": key,
            "dtype": _dtype_token(arr.dtype),
            "shape": list(arr.shape),
            "nbytes": int(arr.nbytes),
        })
        buffers.append(arr.tobytes())
    head = json.dumps(header).encode()
    return b"".join(
        [_WIRE_MAGIC, struct.pack("<I", len(head)), head, *buffers]
    )


def page_span_from_wire(blob: bytes) -> Dict[str, Any]:
    """Parse ``page_span_to_wire`` output back into the payload dict.
    Raises ValueError on a torn or foreign blob — the ingest endpoint maps
    that to a clean 400, never a handler traceback."""
    if len(blob) < len(_WIRE_MAGIC) + 4 or not blob.startswith(_WIRE_MAGIC):
        raise ValueError("not a page-span wire blob")
    off = len(_WIRE_MAGIC)
    (head_len,) = struct.unpack_from("<I", blob, off)
    off += 4
    try:
        header = json.loads(blob[off : off + head_len])
    except ValueError as exc:
        raise ValueError(f"torn page-span header: {exc}") from exc
    off += head_len
    leaves: Dict[str, np.ndarray] = {}
    for meta in header.pop("leaves", []):
        n = int(meta["nbytes"])
        if off + n > len(blob):
            raise ValueError("page-span blob truncated mid-buffer")
        leaves[meta["key"]] = np.frombuffer(
            blob[off : off + n], dtype=_dtype_from_token(meta["dtype"])
        ).reshape(meta["shape"])
        off += n
    header["leaves"] = leaves
    return header


@functools.partial(jax.jit, donate_argnums=(0,))
def _copy_page(cache: Any, src: jax.Array, dst: jax.Array) -> Any:
    """Copy pool page ``src`` onto ``dst`` in every K/V pool leaf, one
    dispatch — the copy-on-write primitive. The page axis sits at
    ``ndim - 4`` in every pool layout this repo produces (per-layer
    [n_pages, page, KVH, D|1], scanned [L, n_pages, page, KVH, D|1])."""

    def one(path, leaf):
        if _leaf_name(path) not in POOL_LEAVES:
            return leaf
        ax = leaf.ndim - 4
        row = jax.lax.dynamic_slice_in_dim(leaf, src, 1, axis=ax)
        return jax.lax.dynamic_update_slice_in_dim(leaf, row, dst, axis=ax)

    return jax.tree_util.tree_map_with_path(one, cache)


class PagePool:
    """Host-side page allocator: free list + per-page refcounts.

    Page 0 is the TRASH page — never allocated, always mapped by zeroed
    block-table rows, so parked/inactive rows in a fixed-shape dispatch
    write somewhere harmless (their reads are masked by validity anyway).

    ``reserved`` tracks pages PROMISED to admitted slots but not yet drawn:
    admission reserves a request's worst case (prompt + budget + draft
    headroom) up front, so a slot that was admitted can never hit a
    mid-decode out-of-pages fault — capacity pressure surfaces as requests
    WAITING in the queue, the honest backpressure signal the capacity sweep
    measures.
    """

    TRASH = 0

    def __init__(self, n_pages: int):
        if n_pages < 2:
            raise ValueError("n_pages must be >= 2 (trash + at least one real)")
        self.n_pages = n_pages
        self.refs = [0] * n_pages
        self._free: List[int] = list(range(1, n_pages))
        self.reserved = 0
        self.peak_in_use = 0

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def in_use(self) -> int:
        return (self.n_pages - 1) - len(self._free)

    @property
    def available(self) -> int:
        """Pages neither allocated nor promised to an admitted slot."""
        return len(self._free) - self.reserved

    def alloc(self) -> Optional[int]:
        if not self._free:
            return None
        page = self._free.pop()
        self.refs[page] = 1
        self.peak_in_use = max(self.peak_in_use, self.in_use)
        return page

    def incref(self, pages) -> None:
        for p in pages:
            if p == self.TRASH or self.refs[p] < 1:
                raise ValueError(f"incref of unallocated page {p}")
            self.refs[p] += 1

    def decref(self, pages) -> int:
        """Drop one reference per page; pages reaching zero return to the
        free list. Returns how many were actually freed."""
        freed = 0
        for p in pages:
            if p == self.TRASH:
                continue
            if self.refs[p] < 1:
                raise ValueError(f"decref of free page {p}")
            self.refs[p] -= 1
            if self.refs[p] == 0:
                self._free.append(p)
                freed += 1
        return freed


class PagedKVCache:
    """Paged drop-in for ``SlotKVCache``: same slot bookkeeping surface
    (acquire / release / free_count / insert-less chunked fill), but K/V
    lives in the model's page pool and each slot's rows are a block table.

    Device state: ``self.cache`` (pool leaves + ``block_table`` + vector
    index leaves). Host state: the authoritative block-table mirror
    (``self.table``), per-slot allocation/reservation counts, and the
    ``PagePool``. Only the engine's tick thread touches any of it.
    """

    def __init__(self, model, n_slots: int, mesh=None):
        if n_slots < 1:
            raise ValueError("n_slots must be >= 1")
        if model.kv_pages is None:
            raise ValueError("PagedKVCache needs a paged decode model (kv_pages)")
        self.model = model
        self.n_slots = n_slots
        self.mesh = mesh
        self.n_pages, self.page_size = model.kv_pages
        cap = model.cache_len or model.cfg.max_seq_len
        self.seq_capacity = cap
        self.n_blocks = cap // self.page_size
        self.pool = PagePool(self.n_pages)
        # host mirror of every row's block table; zeros = trash page
        self.table = np.zeros((n_slots, self.n_blocks), np.int32)
        # mapping changed since the last device push (mutators mark, the
        # engine flushes ONCE before any dispatch that reads device tables)
        self.tables_dirty = False
        self.alloc_blocks = [0] * n_slots  # leading blocks mapped, per slot
        self.reserved_blocks = [0] * n_slots  # admission promise, per slot
        self.cache = vectorize_index(
            init_cache(model, n_slots, mesh=mesh), n_slots
        )
        self._free: List[int] = list(range(n_slots))
        self.cow_copies = 0

    # ---- device sync -----------------------------------------------------

    def sync_tables(self) -> None:
        """Push the host block-table mirror into every ``block_table`` leaf
        (per-layer copies under the scanned stack broadcast the same
        values). Tiny int32 traffic; ``flush_tables`` below batches the
        pushes to one per tick."""
        dev = jnp.asarray(self.table)

        def one(path, leaf):
            if _leaf_name(path) == TABLE_LEAF:
                return jnp.broadcast_to(dev, leaf.shape).astype(leaf.dtype)
            return leaf

        self.cache = jax.tree_util.tree_map_with_path(one, self.cache)
        self.tables_dirty = False

    def flush_tables(self) -> None:
        """One device push for every mapping change since the last flush.
        MUST run before any dispatch that reads the device tables (the
        fused decode / spec step); the paged chunk program is exempt — it
        takes the host table as an argument and overwrites the device
        leaves itself. Batching matters: N slots crossing a page boundary
        on one tick would otherwise pay N separate pushes on the decode
        hot path."""
        if self.tables_dirty:
            self.sync_tables()

    # ---- allocation ------------------------------------------------------

    def blocks_for(self, tokens: int) -> int:
        return -(-tokens // self.page_size)  # ceil

    def can_admit(self, new_blocks: int) -> bool:
        return self.pool.available >= new_blocks

    def reserve(self, slot: int, total_tokens: int) -> None:
        """Promise pages covering ``total_tokens`` logical positions beyond
        what the slot already maps (shared prefix pages included in
        ``alloc_blocks`` by ``share``). Re-reserving replaces the slot's
        previous promise."""
        self._unreserve(slot)
        need = max(0, self.blocks_for(total_tokens) - self.alloc_blocks[slot])
        self.reserved_blocks[slot] = need
        self.pool.reserved += need

    def _unreserve(self, slot: int) -> None:
        self.pool.reserved -= self.reserved_blocks[slot]
        self.reserved_blocks[slot] = 0

    def ensure(self, slot: int, tokens: int) -> bool:
        """Map fresh pages so the slot's table covers positions
        ``[0, tokens)``; draws down the slot's reservation. Returns False
        when the pool is exhausted (the engine reclaims prefix-cache pages
        and retries, then preempts)."""
        need = self.blocks_for(tokens)
        while self.alloc_blocks[slot] < need:
            page = self.pool.alloc()
            if page is None:
                return False
            b = self.alloc_blocks[slot]
            self.table[slot, b] = page
            self.alloc_blocks[slot] = b + 1
            if self.reserved_blocks[slot] > 0:
                self.reserved_blocks[slot] -= 1
                self.pool.reserved -= 1
            self.tables_dirty = True
        return True

    def share(self, slot: int, pages: Sequence[int]) -> None:
        """Prefix hit: map ``pages`` as the slot's leading blocks and bump
        their refcounts — K/V reuse without moving a byte."""
        if not pages:
            return
        if self.alloc_blocks[slot] != 0:
            raise ValueError("share() must precede any allocation for the slot")
        self.pool.incref(pages)
        for b, p in enumerate(pages):
            self.table[slot, b] = p
        self.alloc_blocks[slot] = len(pages)
        self.tables_dirty = True

    def bank(self, slot: int, n_blocks: int) -> List[int]:
        """Page ids of the slot's first ``n_blocks`` blocks, refcounts
        bumped for the prefix index's hold (the caller stores them)."""
        pages = [int(p) for p in self.table[slot, :n_blocks]]
        self.pool.incref(pages)
        return pages

    def cow(self, slot: int, block: int) -> bool:
        """Copy-on-write guard: if the slot is about to WRITE into a shared
        page, give it a private copy first. Chunk-aligned sharing makes
        this unreachable in the steady state (divergence starts at a page
        boundary), but the guard keeps 'shared pages are never written with
        divergent data' a local invariant instead of a global proof."""
        if block >= self.alloc_blocks[slot]:
            return True
        page = int(self.table[slot, block])
        if page == PagePool.TRASH or self.pool.refs[page] <= 1:
            return True
        fresh = self.pool.alloc()
        if fresh is None:
            return False
        self.cache = _copy_page(
            self.cache, jnp.int32(page), jnp.int32(fresh)
        )
        self.table[slot, block] = fresh
        self.pool.decref([page])
        self.cow_copies += 1
        self.tables_dirty = True
        return True

    # ---- transferable page spans (export / import) -----------------------

    def _quantized_blocks(self, count: int) -> int:
        """Gather/scatter page counts are STATIC in the compiled transfer
        ops — quantize to the next power of two (capped at the per-slot
        block capacity) so the compile family stays ~log2(n_blocks)."""
        b = 1
        while b < count:
            b *= 2
        return min(b, max(1, self.n_blocks))

    # graftlint: hot-path
    def export_page_span(self, slot: int, n_tokens: int) -> Dict[str, Any]:
        """HOST-side image of the slot's leading pages covering positions
        ``[0, n_tokens)``: raw K/V bytes per pool leaf (int8 scales
        included) + the block-table fragment geometry. Read-only — the
        slot keeps its pages and refcounts are untouched, so an export
        followed by a failed ship leaves the source stream intact."""
        n_blocks = self.blocks_for(n_tokens)
        if n_blocks > self.alloc_blocks[slot]:
            raise ValueError(
                f"slot {slot} maps {self.alloc_blocks[slot]} blocks; "
                f"export of {n_blocks} requested"
            )
        pages = [int(p) for p in self.table[slot, :n_blocks]]
        padded = self._quantized_blocks(n_blocks)
        ids = pages + [PagePool.TRASH] * (padded - n_blocks)
        spans = _gather_pages_impl(self.cache, jnp.asarray(ids, jnp.int32))
        # graftlint: allow[host-sync-in-hot-path] reason=THE designed migration-send sync — one coalesced device_get of the whole span, off the engine lock, only when a stream actually migrates
        host = jax.device_get(spans)
        return {
            "page_size": self.page_size,
            "n_blocks": n_blocks,
            "n_tokens": int(n_tokens),
            "leaves": {k: v[:n_blocks] for k, v in host.items()},
        }

    # graftlint: hot-path
    def import_page_span(self, slot: int, payload: Dict[str, Any]) -> bool:
        """Materialize an exported span as ``slot``'s leading blocks:
        allocate fresh pages, scatter the bytes in (ONE dispatch), and map
        them in the host table. Bit-exact by construction (raw bytes, same
        dtypes). Returns False when the pool cannot cover the span (the
        caller falls back or waits); raises ValueError on a structurally
        incompatible payload (page size / leaf geometry mismatch — that is
        a wrong-fleet bug, not a capacity condition).

        Imported pages are ordinary refcounted pool pages (ref 1, owned by
        the slot): bank/share them and the standard copy-on-write guard
        protects any post-import write to a shared page."""
        if self.alloc_blocks[slot] != 0:
            raise ValueError("import_page_span needs an empty slot")
        # graftlint: allow[host-sync-in-hot-path] reason=wire-payload fields are host ints (json header), never device values
        page_size, n_blocks = int(payload["page_size"]), int(payload["n_blocks"])
        if page_size != self.page_size:
            raise ValueError(
                f"page-span page_size {page_size} != pool "
                f"page_size {self.page_size}"
            )
        if n_blocks > self.n_blocks:
            raise ValueError(
                f"span of {n_blocks} blocks exceeds per-slot capacity "
                f"{self.n_blocks}"
            )
        leaves = payload["leaves"]
        expect = {}
        for path, leaf in jax.tree_util.tree_leaves_with_path(self.cache):
            if _leaf_name(path) in POOL_LEAVES:
                key = jax.tree_util.keystr(path)
                ax = leaf.ndim - 4
                shape = tuple(
                    d for i, d in enumerate(leaf.shape) if i != ax
                )
                expect[key] = (shape, leaf.dtype)
        if set(leaves) != set(expect):
            raise ValueError(
                f"page-span leaves {sorted(leaves)} != pool leaves "
                f"{sorted(expect)}"
            )
        for key, arr in leaves.items():
            shape, dtype = expect[key]
            if tuple(arr.shape) != (n_blocks,) + shape or np.dtype(
                arr.dtype
            ) != np.dtype(dtype):
                raise ValueError(
                    f"page-span leaf {key} is {arr.dtype}{arr.shape}; "
                    f"pool expects {np.dtype(dtype).str}[{n_blocks}]+{shape}"
                )
        fresh: List[int] = []
        for _ in range(n_blocks):
            page = self.pool.alloc()
            if page is None:
                self.pool.decref(fresh)  # roll the partial allocation back
                return False
            fresh.append(page)
        padded = self._quantized_blocks(n_blocks)
        ids = fresh + [PagePool.TRASH] * (padded - n_blocks)
        spans = {}
        for key, arr in leaves.items():
            pad = np.zeros(
                (padded - n_blocks,) + arr.shape[1:], dtype=arr.dtype
            )
            spans[key] = jnp.asarray(np.concatenate([arr, pad], axis=0))
        self.cache = _scatter_pages_impl(
            self.cache, jnp.asarray(ids, jnp.int32), spans
        )
        for b, p in enumerate(fresh):
            self.table[slot, b] = p
        self.alloc_blocks[slot] = n_blocks
        self.tables_dirty = True
        return True

    def set_cursor(self, slot: int, value: int) -> None:
        """Set the slot's fill cursor in every index leaf (import install:
        the host knows the migrated stream's exact position)."""
        self.cache = _set_index_slot(
            self.cache, jnp.int32(slot), jnp.int32(value)
        )

    def reset_slot_pages(self, slot: int) -> None:
        """Drop every page the slot maps WITHOUT freeing the slot itself
        (hot-reload prefill restart: shared pre-reload pages must not be
        rewritten under new weights). The caller re-reserves."""
        n = self.alloc_blocks[slot]
        if not n:
            return
        self.pool.decref(int(p) for p in self.table[slot, :n])
        self.table[slot, :n] = 0
        self.alloc_blocks[slot] = 0
        self.tables_dirty = True

    # ---- slot bookkeeping (SlotKVCache-compatible surface) ---------------

    @property
    def free_count(self) -> int:
        return len(self._free)

    @property
    def active_count(self) -> int:
        return self.n_slots - len(self._free)

    @property
    def page_pool_util(self) -> float:
        real = self.n_pages - 1
        return self.pool.in_use / real if real else 0.0

    def acquire(self) -> Optional[int]:
        return self._free.pop(0) if self._free else None

    def release(self, slots: List[int]) -> None:
        """Retire slots: drop their page references (pages a cached prefix
        still holds survive), zero their table rows and index cursors."""
        if not slots:
            return
        for s in slots:
            if s in self._free:
                raise ValueError(f"slot {s} double-released")
            n = self.alloc_blocks[s]
            if n:
                self.pool.decref(int(p) for p in self.table[s, :n])
                self.table[s, :n] = 0
                self.alloc_blocks[s] = 0
                self.tables_dirty = True
            self._unreserve(s)
            self._free.append(s)
        keep = jnp.asarray(
            [s not in self._free for s in range(self.n_slots)], jnp.bool_
        )
        self.cache = _reset_index(self.cache, keep)
