"""Fault-tolerant fleet router: a control-plane tier over N engine replicas.

Everything below this module hardens ONE ``ServingEngine`` replica (PR 3:
lifecycle /healthz, supervised ticks, drain, hot reload). This router is the
tier that makes a *fleet* of them survive what a single process cannot:
replica death mid-stream, slow/sick replicas, and fleet-wide weight rollouts
— the ROADMAP item-3 control plane. Stdlib-only HTTP (same discipline as
``server.py``), so the fleet surface runs anywhere the replicas do.

Pieces, each independently unit-testable without sockets:

- **ReplicaRegistry**: active health probing of each replica's ``/healthz``,
  honoring the PR 3 lifecycle states — READY routes, DEGRADED stays in
  rotation but deprioritized, DRAINING/STOPPED leave rotation (they answer,
  so they are *not* probe failures). Consecutive probe failures feed a
  per-replica ``CircuitBreaker`` (the PR 3 primitive, reused); a trip EJECTS
  the replica with exponential-backoff re-probing, and one successful probe
  recovers it. The probe also scrapes the replica's admission inputs
  (``itl_ewma_ms``, ``queue_depth``, ``active_slots``, ``free_pages`` —
  served in the ``/healthz`` body exactly so the router needs one cheap
  poll, not a ``/metrics`` scrape).
- **Routing policy** (pure functions): prefix-aware first — the prompt's
  chunk-aligned token prefix is mapped to the replica that served it last
  (``PrefixAffinity``), so repeated/shared prefixes land where their K/V is
  already cached and N per-replica prefix caches behave like one
  distributed cache. Affinity only holds within the healthy pool: a READY
  replica always beats a DEGRADED one, and ties break by least-loaded
  admission (scraped queue depth + active slots + the router's own
  in-flight relays, weighted by the replica's measured ITL EWMA).
- **Failover**: requests relay with bounded retry + backoff across
  replicas. Pre-stream failures (connect refused, 5xx/429) simply try the
  next replica. The hard case is **mid-stream** death: the router counts
  every token it has relayed, and when a replica dies under an active SSE
  stream it re-dispatches the request to a survivor with ``prompt +
  generated-so-far`` as the new prompt and the token budget reduced by what
  was already delivered — the client sees a stall, then the stream resumes
  (greedy sampling continues the exact trajectory; seeded stochastic
  sampling continues *a* consistent trajectory). Non-resumable cases (text
  prompt the router cannot re-tokenize, retry budget exhausted) terminate
  with a retryable SSE error event — never a silent hang.
- **Rolling fleet reload** (``POST /admin/reload`` on the router): one
  replica at a time is cordoned (no new requests routed to it), the
  router's in-flight relays to it drain to zero, the replica hot-reloads
  via its own PR 3 ``/admin/reload`` path, the router waits for READY, then
  uncordons and moves on — ``dropped_streams == 0`` by construction, chaos-
  proven in ``tests/test_router.py`` / ``make router-chaos``.

Observability: the router carries its own ``Tracer`` (every relayed request
gets a span tree on its ``X-Request-Id`` track, each hop tagged with the
``replica`` that served it — a Perfetto view shows exactly which replicas a
failover crossed), a Prometheus ``Registry`` (``GET /metrics`` content-
negotiates JSON vs text exposition like the replica server), and a
``FlightRecorder`` that dumps the recent probe/relay window whenever a
replica is ejected. ``X-Request-Id`` propagates verbatim: client → router →
replica → back, so one id keys the request's spans on every tier.
"""
from __future__ import annotations

import dataclasses
import http.client
import json
import math
import re
import threading
import time
import uuid
from collections import OrderedDict
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple
from urllib.parse import urlsplit

from zero_transformer_tpu.obs.flight import FlightRecorder
from zero_transformer_tpu.obs.fleet import (
    FleetAggregator,
    TenantLedger,
    complete_ledger,
    estimate_clock_offset,
    request_ids_in,
    stitch_spans,
    verify_stitched,
)
from zero_transformer_tpu.obs.metrics import Registry
from zero_transformer_tpu.obs.slo import (
    Objective,
    SLOEngine,
    default_objectives,
    parse_slo_config,
)
from zero_transformer_tpu.obs.spans import Tracer
from zero_transformer_tpu.serving.qos import (
    BrownoutController,
    QosPolicy,
    TenantBuckets,
    rung_at_least,
)
from zero_transformer_tpu.serving.resilience import (
    DEGRADED,
    DRAINING,
    READY,
    CircuitBreaker,
)

# Replica states as the ROUTER sees them (a superset of the replica's own
# lifecycle: the router must also represent "I cannot reach it at all").
UNKNOWN = "unknown"  # never probed successfully yet
EJECTED = "ejected"  # consecutive probe failures tripped the breaker

# EXACTLY the engine's charset (engine.py _RID_UNSAFE): the id must survive
# router -> replica re-sanitation verbatim or cross-tier span correlation
# silently breaks for the characters the tiers disagree on
_RID_UNSAFE = re.compile(r"[^A-Za-z0-9._:/=-]")


def _clean_rid(request_id: Optional[str]) -> str:
    """Same header-safe sanitation as the engine: the id is echoed into a
    response header, so CR/LF injection and non-latin-1 must be impossible."""
    if request_id:
        clean = _RID_UNSAFE.sub("", str(request_id))[:128]
        if clean:
            return clean
    return uuid.uuid4().hex


# ------------------------------------------------------------------ registry


@dataclasses.dataclass
class Replica:
    """One replica as the router tracks it: identity, probed lifecycle
    state, scraped admission inputs, and router-side relay bookkeeping."""

    id: str
    url: str
    host: str
    port: int
    state: str = UNKNOWN
    cordoned: bool = False  # rolling reload: out of rotation, not ejected
    consecutive_failures: int = 0
    ejections: int = 0
    backoff_s: float = 0.0
    next_probe_at: float = 0.0
    last_probe_at: Optional[float] = None
    # admission inputs scraped from the replica's /healthz body (satellite:
    # the body carries them so routing costs one poll, not a /metrics scrape)
    itl_ewma_ms: float = 0.0
    queue_depth: int = 0
    active_slots: int = 0
    free_pages: int = 0
    breaker_open: bool = False
    # disaggregation: the replica's engine role (prefill/decode/mixed) and
    # its in-flight page shipments, both scraped from /healthz — plus the
    # page-pool pressure stats the router mirrors as per-replica gauges
    role: str = "mixed"
    migrations_in_flight: int = 0
    page_faults: int = 0
    cow_copies: int = 0
    # importability: pages can only ship to a paged-layout engine; "" until
    # the first successful probe (treated as NOT importable — never ship
    # into the unknown). ``draft_k`` rides along because an import's
    # veto/rewind carry is draft_k-shaped — a mismatched target rejects
    # every ship, so placement filters on it up front.
    kv_layout: str = ""
    draft_k: int = 0
    # per-process clock offset (replica monotonic clock minus the router's,
    # PR 15): estimated NTP-style from each probe's round trip against the
    # ``clock_monotonic`` the /healthz body carries; the trace stitcher
    # subtracts it to place this replica's spans on the fleet timeline.
    # rtt is the error bar (the true offset is within ±rtt/2).
    clock_offset_s: float = 0.0
    clock_rtt_s: float = float("inf")
    clock_at: float = 0.0

    @property
    def importable(self) -> bool:
        return self.kv_layout == "paged"
    # router-side live view (fresher than the last probe)
    active_relays: int = 0
    tokens_relayed: int = 0
    requests_routed: int = 0
    breaker: CircuitBreaker = dataclasses.field(
        default_factory=lambda: CircuitBreaker(threshold=3, cooldown=1)
    )

    @property
    def routable(self) -> bool:
        return self.state in (READY, DEGRADED) and not self.cordoned

    def load_score(self) -> Tuple[float, int, str]:
        """Estimated backlog drain time: requests ahead (scraped queue +
        active slots + the router's own in-flight relays) weighted by the
        replica's measured ITL EWMA. The EWMA floor keeps a cold replica
        (no samples yet) attractive without dividing by zero; the id
        tie-break keeps the policy deterministic."""
        backlog = self.queue_depth + self.active_slots + self.active_relays
        return (backlog * max(self.itl_ewma_ms, 0.1), backlog, self.id)


def _parse_url(url: str) -> Tuple[str, str, int]:
    parts = urlsplit(url if "//" in url else f"http://{url}")
    host = parts.hostname or "127.0.0.1"
    port = parts.port or 80
    return f"{host}:{port}", host, port


class ReplicaRegistry:
    """Thread-safe replica table + the probe-outcome state machine.

    Pure logic: no sockets. The server feeds it probe outcomes
    (``observe_probe``) and relay failures (``observe_relay_failure``); it
    answers "who is due a probe" (``due``, honoring the exponential backoff
    of ejected replicas) and "who can take traffic" (``routable``).
    """

    def __init__(
        self,
        urls: Sequence[str],
        clock=time.monotonic,
        probe_interval: float = 0.25,
        eject_threshold: int = 3,
        backoff_base_s: float = 0.5,
        backoff_max_s: float = 8.0,
    ):
        if not urls:
            raise ValueError("router needs at least one replica URL")
        if eject_threshold < 1:
            raise ValueError("eject_threshold must be >= 1")
        self.clock = clock
        self.probe_interval = probe_interval
        self.eject_threshold = eject_threshold
        self.backoff_base_s = backoff_base_s
        self.backoff_max_s = backoff_max_s
        self._lock = threading.Lock()
        self.replicas: "OrderedDict[str, Replica]" = OrderedDict()
        for url in urls:
            rid, host, port = _parse_url(url)
            if rid in self.replicas:
                raise ValueError(f"duplicate replica {rid}")
            self.replicas[rid] = Replica(
                id=rid, url=url, host=host, port=port,
                breaker=CircuitBreaker(threshold=eject_threshold, cooldown=1),
            )

    def __len__(self) -> int:
        return len(self.replicas)

    # ------------------------------------------------------------- observing

    def observe_probe(
        self,
        rid: str,
        ok: bool,
        code: Optional[int] = None,
        body: Optional[dict] = None,
        rtt_window: Optional[Tuple[float, float]] = None,
    ) -> List[Tuple[str, str]]:
        """Fold one probe outcome into the replica's state. ``ok`` means the
        probe got an HTTP response with a parseable body (whatever the
        status code — a 503 from a draining replica is an ANSWER, not a
        failure). Returns lifecycle events for the caller to surface:
        ``("ejected", rid)`` / ``("recovered", rid)``."""
        now = self.clock()
        events: List[Tuple[str, str]] = []
        # parse the remote clock OUTSIDE the lock (lint: no conversions of
        # foreign values while holding the registry lock)
        clock_remote: Optional[float] = None
        if body is not None and body.get("clock_monotonic") is not None:
            try:
                clock_remote = float(body["clock_monotonic"])
            except (TypeError, ValueError):
                clock_remote = None
        with self._lock:
            r = self.replicas.get(rid)
            if r is None:
                return events  # removed (autoscale retire) mid-probe
            r.last_probe_at = now
            if ok:
                state = str((body or {}).get("state", ""))
                was_ejected = r.state == EJECTED
                r.breaker.record_clean()
                r.consecutive_failures = 0
                r.backoff_s = 0.0
                if state == READY:
                    r.state = READY
                elif state == DEGRADED:
                    r.state = DEGRADED
                elif state in (DRAINING, "stopped", "scheduler dead"):
                    # answers, but is leaving: out of rotation without the
                    # ejection machinery (no backoff — it may restart READY)
                    r.state = DRAINING
                else:  # "starting" or an unrecognized body
                    r.state = UNKNOWN
                if was_ejected and r.state in (READY, DEGRADED):
                    events.append(("recovered", rid))
                if body:
                    r.itl_ewma_ms = float(body.get("itl_ewma_ms", 0.0) or 0.0)
                    r.queue_depth = int(
                        body.get("queue_depth", body.get("queued", 0)) or 0
                    )
                    r.active_slots = int(
                        body.get("active_slots", body.get("active", 0)) or 0
                    )
                    r.free_pages = int(body.get("free_pages", 0) or 0)
                    r.breaker_open = bool(body.get("breaker_open", False))
                    r.role = str(body.get("role", "mixed") or "mixed")
                    r.migrations_in_flight = int(
                        body.get("migrations_in_flight", 0) or 0
                    )
                    r.page_faults = int(body.get("page_faults", 0) or 0)
                    r.cow_copies = int(body.get("cow_copies", 0) or 0)
                    r.kv_layout = str(body.get("kv_layout", "") or "")
                    r.draft_k = int(body.get("draft_k", 0) or 0)
                    if rtt_window is not None and clock_remote is not None:
                        # per-process clock offset from this round trip
                        # (keeps the tighter-rtt estimate until it ages)
                        prev = (
                            None if r.clock_rtt_s == float("inf")
                            else (r.clock_offset_s, r.clock_rtt_s, r.clock_at)
                        )
                        r.clock_offset_s, r.clock_rtt_s, r.clock_at = (
                            estimate_clock_offset(
                                clock_remote,
                                rtt_window[0], rtt_window[1],
                                prev=prev, now=now,
                            )
                        )
                r.next_probe_at = now + self.probe_interval
            else:
                r.consecutive_failures += 1
                tripped = r.breaker.record_fault()
                if r.state == EJECTED:
                    # still dead on a backed-off re-probe: double the wait
                    r.backoff_s = min(r.backoff_s * 2.0, self.backoff_max_s)
                    r.next_probe_at = now + r.backoff_s
                elif tripped:
                    r.state = EJECTED
                    r.ejections += 1
                    r.backoff_s = self.backoff_base_s
                    r.next_probe_at = now + r.backoff_s
                    events.append(("ejected", rid))
                else:
                    r.next_probe_at = now + self.probe_interval
        return events

    def observe_relay_failure(self, rid: str, reason: str = "") -> List[Tuple[str, str]]:
        """A relay hit a dead connection: count it like a probe failure (the
        relay IS evidence of unreachability) and schedule an immediate
        re-probe so the registry converges faster than the probe interval."""
        events = self.observe_probe(rid, ok=False)
        with self._lock:
            r = self.replicas.get(rid)
            if r is not None and r.state != EJECTED:
                r.next_probe_at = self.clock()  # probe now, not next tick
        return events

    # --------------------------------------------------------------- queries

    def due(self, now: Optional[float] = None) -> List[Replica]:
        """Replicas whose next probe is due (ejected ones respect their
        exponential backoff; everyone else the base interval)."""
        t = self.clock() if now is None else now
        with self._lock:
            return [r for r in self.replicas.values() if r.next_probe_at <= t]

    def routable(self) -> List[Replica]:
        with self._lock:
            return [r for r in self.replicas.values() if r.routable]

    def get(self, rid: str) -> Replica:
        return self.replicas[rid]

    # ------------------------------------------------------- fleet elasticity

    def add(self, url: str, replace: bool = False) -> str:
        """Register a new replica (autoscale spawn): it enters UNKNOWN and
        joins rotation on its first clean READY probe. Returns its id.

        ``replace=True`` re-registers an EXISTING id with a completely
        fresh row (fresh breaker, no cordon, zeroed failure counts). A
        process that died and came back under the same identity — a
        SIGKILLed training worker rejoining the fleet, a replica restarted
        in place — must not inherit its dead predecessor's cordon or
        tripped breaker: that stale state would keep the NEW process out of
        rotation forever (pinned by tests/test_router.py). The default
        stays ``False`` for idempotent admin adds: re-adding a LIVE replica
        mid-drain must not silently uncordon it."""
        rid, host, port = _parse_url(url)
        with self._lock:
            if rid in self.replicas and not replace:
                return rid
            self.replicas[rid] = Replica(
                id=rid, url=url, host=host, port=port,
                breaker=CircuitBreaker(
                    threshold=self.eject_threshold, cooldown=1
                ),
            )
        return rid

    def remove(self, rid: str) -> None:
        """Forget a replica (autoscale retire). The caller owns cordoning
        and draining/migrating first — removal is pure bookkeeping."""
        with self._lock:
            self.replicas.pop(rid, None)

    # -------------------------------------------------- router-side bookkeeping

    def cordon(self, rid: str) -> None:
        with self._lock:
            if rid in self.replicas:
                self.replicas[rid].cordoned = True

    def uncordon(self, rid: str) -> None:
        with self._lock:
            if rid in self.replicas:
                self.replicas[rid].cordoned = False

    def inc_relay(self, rid: str) -> None:
        with self._lock:
            r = self.replicas.get(rid)
            if r is not None:
                r.active_relays += 1
                r.requests_routed += 1

    def dec_relay(self, rid: str) -> None:
        with self._lock:
            r = self.replicas.get(rid)
            if r is not None:
                r.active_relays -= 1

    def add_tokens(self, rid: str, n: int) -> None:
        with self._lock:
            r = self.replicas.get(rid)
            if r is not None:
                r.tokens_relayed += n

    def snapshot(self) -> Dict[str, Dict[str, Any]]:
        with self._lock:
            return {
                r.id: {
                    "url": r.url,
                    "state": r.state,
                    "cordoned": r.cordoned,
                    "consecutive_failures": r.consecutive_failures,
                    "ejections": r.ejections,
                    "backoff_s": r.backoff_s,
                    "itl_ewma_ms": r.itl_ewma_ms,
                    "queue_depth": r.queue_depth,
                    "active_slots": r.active_slots,
                    "free_pages": r.free_pages,
                    "role": r.role,
                    "kv_layout": r.kv_layout,
                    "migrations_in_flight": r.migrations_in_flight,
                    "page_faults": r.page_faults,
                    "cow_copies": r.cow_copies,
                    "active_relays": r.active_relays,
                    "tokens_relayed": r.tokens_relayed,
                    "requests_routed": r.requests_routed,
                    "clock_offset_s": r.clock_offset_s,
                    "clock_rtt_s": (
                        r.clock_rtt_s
                        if r.clock_rtt_s != float("inf") else None
                    ),
                }
                for r in self.replicas.values()
            }


# ------------------------------------------------------------ routing policy


def chunk_prefix_key(
    tokens: Optional[Sequence[int]], chunk_tokens: int
) -> Optional[Tuple[int, ...]]:
    """The affinity key: the prompt's LONGEST chunk-aligned token prefix —
    the exact granularity the per-replica prefix cache banks K/V at
    (``prefix_cache.py`` keys entries by whole chunk-aligned prefixes), so
    "same key" really means "that replica has reusable K/V". Prompts
    shorter than one chunk have nothing cacheable to be affine to."""
    if tokens is None or chunk_tokens < 1:
        return None
    n = (len(tokens) // chunk_tokens) * chunk_tokens
    if n == 0:
        return None
    return tuple(int(t) for t in tokens[:n])


# PrefixAffinity keys levels by (length, rolling hash) instead of the prefix
# tuple itself: recording L/chunk levels of materialized tuples is O(L^2)
# time and memory per long prompt; one rolling-hash sweep is O(L) total.
# A collision (~2^-61 birthday odds at LRU capacity) merely routes one
# request to a replica without the prefix — a cache miss, never corruption.
_HASH_MOD = (1 << 61) - 1
_HASH_BASE = 1_000_003


def _level_keys(
    tokens: Optional[Sequence[int]], chunk_tokens: int
) -> List[Tuple[int, int]]:
    """(n_tokens, prefix_hash) for every chunk-aligned prefix of ``tokens``,
    deepest first, in one O(len) pass."""
    if tokens is None or chunk_tokens < 1:
        return []
    n = (len(tokens) // chunk_tokens) * chunk_tokens
    if n == 0:
        return []
    out: List[Tuple[int, int]] = []
    h = 0
    for i in range(n):
        h = (h * _HASH_BASE + int(tokens[i]) + 1) % _HASH_MOD
        if (i + 1) % chunk_tokens == 0:
            out.append((i + 1, h))
    out.reverse()
    return out


class PrefixAffinity:
    """Bounded LRU of chunk-aligned prefix keys -> the replica that served
    them last, with LONGEST-match lookup: a route records every aligned
    prefix level of the prompt (``tokens[:chunk]``, ``tokens[:2*chunk]``,
    ...), and a lookup walks its own levels deepest-first — so two prompts
    sharing a system prefix but diverging in their tails still land on the
    same replica (the one whose prefix cache holds the shared chunks).
    Host-side bookkeeping only; a stale entry is harmless (the pick falls
    back to least-loaded when the remembered replica is unhealthy)."""

    def __init__(self, chunk_tokens: int, capacity: int = 4096):
        self.chunk_tokens = max(0, int(chunk_tokens))
        self.capacity = max(1, int(capacity))
        self._map: "OrderedDict[Tuple[int, int], str]" = OrderedDict()
        self._lock = threading.Lock()

    def __len__(self) -> int:
        return len(self._map)

    def _levels(
        self, tokens: Optional[Sequence[int]]
    ) -> List[Tuple[int, int]]:
        """Every chunk-aligned prefix level of ``tokens`` as an O(1)-sized
        (length, hash) key, deepest first."""
        return _level_keys(tokens, self.chunk_tokens)

    def lookup(self, tokens: Optional[Sequence[int]]) -> Optional[str]:
        with self._lock:
            for key in self._levels(tokens):
                rid = self._map.get(key)
                if rid is not None:
                    self._map.move_to_end(key)
                    return rid
        return None

    def record(self, tokens: Optional[Sequence[int]], rid: str) -> None:
        with self._lock:
            for key in self._levels(tokens):
                self._map[key] = rid
                self._map.move_to_end(key)
            while len(self._map) > self.capacity:
                self._map.popitem(last=False)

    def forget_replica(self, rid: str) -> None:
        """Drop every affinity pointing at a replica (its cache is gone:
        ejection, reload — the next request should re-spread, not chase a
        cold or dead replica)."""
        with self._lock:
            for key in [k for k, v in self._map.items() if v == rid]:
                del self._map[key]


def pick_replica(
    candidates: Sequence[Replica], affinity_id: Optional[str] = None
) -> Optional[Replica]:
    """The routing decision, pure: READY beats DEGRADED (a DEGRADED replica
    serves only when nothing READY exists — it is mid-rebuild and slow);
    within the chosen tier, prefix affinity wins (its K/V is there), else
    least-loaded by ``Replica.load_score``. Deterministic for tests."""
    ready = [c for c in candidates if c.state == READY]
    pool = ready or [c for c in candidates if c.state == DEGRADED]
    if not pool:
        return None
    if affinity_id is not None:
        for c in pool:
            if c.id == affinity_id:
                return c
    return min(pool, key=Replica.load_score)


def pick_decode_replica(candidates: Sequence[Replica]) -> Optional[Replica]:
    """Decode PLACEMENT for a disaggregated handoff, pure: most free KV
    pages first (the pages are about to land there), then lowest measured
    ITL EWMA (the stream lives out its decode at that pace), then the
    least-loaded tie-break. READY beats DEGRADED as everywhere else."""
    ready = [c for c in candidates if c.state == READY]
    pool = ready or [c for c in candidates if c.state == DEGRADED]
    if not pool:
        return None
    return min(
        pool,
        key=lambda c: (-c.free_pages, c.itl_ewma_ms, c.load_score()),
    )


# ------------------------------------------------------------------- server


class _HopDead(Exception):
    """The current replica hop failed in a way failover should handle."""


class RouterServer:
    """The router process: HTTP front end + health-probe loop + relay core.

    Endpoints (mirroring the replica surface where it makes sense):

    - ``POST /generate``: relayed to a replica chosen by the routing
      policy; SSE streams pass through token-by-token with mid-stream
      failover; JSON (non-stream) requests retry wholesale on failure.
    - ``GET /healthz``: 200 while >= 1 replica is routable; body carries
      the full per-replica registry snapshot (states, failures, load).
    - ``GET /metrics``: JSON snapshot, or Prometheus text exposition under
      the same content negotiation as the replica server.
    - ``POST /admin/reload``: rolling fleet reload (loopback/bearer-token
      gated like the replica admin surface).
    """

    def __init__(
        self,
        replicas: Sequence[str],
        host: str = "127.0.0.1",
        port: int = 0,
        *,
        probe_interval: float = 0.25,
        probe_timeout: float = 1.0,
        eject_threshold: int = 3,
        backoff_base_s: float = 0.5,
        backoff_max_s: float = 8.0,
        chunk_tokens: int = 8,
        affinity_capacity: int = 4096,
        max_attempts: int = 3,
        retry_backoff_s: float = 0.05,
        connect_timeout: float = 2.0,
        stream_timeout: float = 30.0,
        max_body_bytes: int = 1 << 20,
        admin_token: Optional[str] = None,
        obs_dir: Optional[str] = None,
        trace: bool = True,
        trace_capacity: int = 8192,
        clock=time.monotonic,
        disaggregate: str = "auto",
        migrate_drain: bool = True,
        scaler=None,
        autoscale_interval: float = 0.0,
        min_replicas: int = 1,
        max_replicas: int = 8,
        scale_up_queue: float = 4.0,
        scale_up_itl_ms: float = 0.0,
        scale_up_free_pages: int = 0,
        scale_down_active: int = 0,
        scale_patience: int = 3,
        scale_drain_timeout_s: float = 15.0,
        metrics_scrape_interval: float = 1.0,
        slo: Optional[Sequence] = None,
        slo_eval_interval: float = 0.5,
        tenant_ledger_capacity: int = 1024,
    ):
        self.clock = clock
        self.probe_timeout = probe_timeout
        self.max_attempts = max(1, int(max_attempts))
        self.retry_backoff_s = retry_backoff_s
        self.connect_timeout = connect_timeout
        self.stream_timeout = stream_timeout
        self.max_body_bytes = max_body_bytes
        self.admin_token = admin_token
        # disaggregated prefill/decode dispatch: "auto" engages whenever the
        # fleet advertises at least one prefill-role AND one decode-capable
        # replica on /healthz; "off" forces the classic single-replica path
        if disaggregate not in ("auto", "off"):
            raise ValueError("disaggregate must be auto|off")
        self.disaggregate = disaggregate
        # drain-as-migrate: rolling reload and autoscale retire ask the
        # replica to SHIP its live streams (zero-recompute handoff) instead
        # of waiting out every in-flight generation; the recompute resume
        # stays as the fallback when the source can't comply
        self.migrate_drain = bool(migrate_drain)
        # autoscaler: a control loop over the load signals every probe
        # already scrapes (queue depth, ITL EWMA, free_pages), acting
        # through ``scaler`` — an object with ``spawn() -> url`` and
        # ``retire(url)`` — and the same cordon/drain machinery the rolling
        # reload rides. Off unless both an interval and a scaler are given.
        self.scaler = scaler
        self.autoscale_interval = float(autoscale_interval)
        self.min_replicas = max(1, int(min_replicas))
        self.max_replicas = max(self.min_replicas, int(max_replicas))
        self.scale_up_queue = float(scale_up_queue)
        self.scale_up_itl_ms = float(scale_up_itl_ms)
        self.scale_up_free_pages = int(scale_up_free_pages)
        self.scale_down_active = int(scale_down_active)
        self.scale_patience = max(1, int(scale_patience))
        self.scale_drain_timeout_s = float(scale_drain_timeout_s)
        self._hot_ticks = 0
        self._idle_ticks = 0
        self.registry = ReplicaRegistry(
            replicas, clock=clock, probe_interval=probe_interval,
            eject_threshold=eject_threshold, backoff_base_s=backoff_base_s,
            backoff_max_s=backoff_max_s,
        )
        self.affinity = PrefixAffinity(chunk_tokens, affinity_capacity)
        self.stats: Dict[str, int] = {
            "requests": 0,
            "streams": 0,
            "json_requests": 0,
            "tokens_relayed": 0,
            "routed": 0,
            "retries": 0,
            "failovers": 0,
            "resumed_streams": 0,
            "aborted_streams": 0,
            "dropped_streams": 0,
            "client_disconnects": 0,
            "rejected_no_replica": 0,
            "rejected_invalid": 0,
            "affinity_hits": 0,
            "affinity_misses": 0,
            "probes": 0,
            "probe_failures": 0,
            "ejections": 0,
            "recoveries": 0,
            "rolling_reloads": 0,
            "reload_steps": 0,
            "reload_failures": 0,
            # disaggregation / migration / autoscale counters
            "disagg_dispatches": 0,
            "disagg_fallbacks": 0,
            "migration_resumes": 0,
            "migrations_requested": 0,
            # tokens the RECOMPUTE fallback re-sent as prompt on a resume
            # hop (an attach resume adds 0 — the zero-replay proof pins
            # this counter)
            "resume_replayed_tokens": 0,
            "autoscale_ups": 0,
            "autoscale_downs": 0,
            "autoscale_aborts": 0,
            # fleet observability plane (PR 15)
            "metrics_scrapes": 0,
            "slo_evaluations": 0,
            "slo_fast_burns": 0,
            "stitched_traces": 0,
            # overload isolation plane (PR 18): fleet-level quota and
            # brownout rejections at the front door, controller rung
            # transitions, tenant-affinity routing, and ledger-eviction
            # honesty (a silently dropped tenant row would under-bill)
            "rejected_quota": 0,
            "rejected_brownout": 0,
            "brownout_transitions": 0,
            "tenant_affinity_hits": 0,
            "tenant_affinity_misses": 0,
            "tenant_ledger_evictions": 0,
        }
        # handler threads bump stats concurrently; += on a dict entry is a
        # read-modify-write, so every increment goes through _bump
        self._stats_lock = threading.Lock()
        self.obs_dir = str(obs_dir) if obs_dir else None
        self.tracer = Tracer(enabled=trace, capacity=trace_capacity, clock=clock)
        self.metrics = Registry()
        self.flight = FlightRecorder(
            directory=self.obs_dir, tracer=self.tracer, clock=clock
        )
        # fleet observability plane (PR 15): the per-replica /metrics
        # scrapes fold into fleet_* rollups, terminal-event cost ledgers
        # roll up per tenant, and the SLO engine evaluates declared
        # objectives over the aggregated streams on the obs loop
        self.metrics_scrape_interval = float(metrics_scrape_interval)
        self.aggregator = FleetAggregator()
        self.tenants = TenantLedger(
            capacity=tenant_ledger_capacity,
            on_evict=self._on_tenant_evicted,
        )
        self.slo_eval_interval = float(slo_eval_interval)
        # overload isolation plane (PR 18): the QoS policy + brownout
        # config ride in the same dict as the SLO objectives (one file,
        # ``configs/slo_default.json``) — a plain objective list still
        # works and leaves the inert default policy in place
        qos_spec = slo.get("qos") if isinstance(slo, dict) else None
        brownout_spec = (
            slo.get("brownout") if isinstance(slo, dict) else None
        ) or {}
        self.qos = QosPolicy.from_config(qos_spec)
        # fleet-level tenant quotas: one bucket set at the front door,
        # scaled by the routable-replica count at take() time so fleet
        # allotment tracks fleet capacity
        self._fleet_buckets = TenantBuckets(self.qos)
        self.brownout = BrownoutController(
            calm_evals=int(brownout_spec.get("calm_evals", 3)),
        )
        protected = brownout_spec.get("protected_classes")
        self._brownout_protected: Tuple[str, ...] = tuple(
            protected if protected else ("gold", "standard")
        )
        # tenant -> replica-id routing affinity (LRU, same bound as the
        # prefix map); prefix affinity is more specific and wins
        self._tenant_affinity: OrderedDict = OrderedDict()
        self._tenant_affinity_capacity = max(1, int(affinity_capacity))
        self._tenant_aff_lock = threading.Lock()
        self.slo = self._build_slo(slo)
        self._slo_hot = False  # fast-burn up-signal the autoscaler consumes
        self._slo_lock = threading.Lock()
        self._obs_thread = threading.Thread(
            target=self._obs_loop, name="router-obs", daemon=True
        )
        self._register_exports()
        self._stop = threading.Event()
        self._reload_busy = threading.Lock()
        self._probe_thread = threading.Thread(
            target=self._probe_loop, name="router-probe", daemon=True
        )
        self._autoscale_thread = threading.Thread(
            target=self._autoscale_loop, name="router-autoscale", daemon=True
        )
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # noqa: A003
                pass

            def _json(self, code: int, obj, headers=None) -> None:
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for name, value in (headers or {}).items():
                    self.send_header(name, value)
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802
                path, _, query = self.path.partition("?")
                if path == "/healthz":
                    self._json(*outer._healthz())
                elif path == "/slo":
                    # the declared objectives' verdict: budget remaining +
                    # burn rate per objective over the aggregated streams
                    self._json(200, outer.slo_snapshot())
                elif path == "/admin/trace":
                    if not outer._admin_allowed(self):
                        self._json(403, {"error": "admin endpoint: loopback "
                                                  "or bearer token required"})
                        return
                    self._json(*outer._admin_trace(query))
                elif path == "/metrics":
                    accept = self.headers.get("Accept") or ""
                    if (
                        "format=prometheus" in query
                        or "text/plain" in accept
                        or "openmetrics" in accept
                    ):
                        # router-local families + the fleet_* rollups the
                        # aggregator folded from the per-replica scrapes:
                        # ONE scrape sees the whole fleet
                        body = (
                            outer.metrics.render() + outer.aggregator.render()
                        ).encode()
                        self.send_response(200)
                        self.send_header(
                            "Content-Type",
                            "text/plain; version=0.0.4; charset=utf-8",
                        )
                        self.send_header("Content-Length", str(len(body)))
                        self.end_headers()
                        self.wfile.write(body)
                    else:
                        self._json(200, outer.metrics_snapshot())
                else:
                    self._json(404, {"error": f"no route {self.path}"})

            def do_POST(self):  # noqa: N802
                if self.path not in (
                    "/generate", "/admin/reload", "/admin/brownout",
                ):
                    self._json(404, {"error": f"no route {self.path}"})
                    return
                try:
                    length = int(self.headers.get("Content-Length", 0))
                except ValueError:
                    self._json(400, {"error": "bad Content-Length"})
                    return
                if length < 0:
                    self._json(400, {"error": "bad Content-Length"})
                    return
                if length > outer.max_body_bytes:
                    self.close_connection = True
                    self._json(413, {
                        "error": f"body exceeds {outer.max_body_bytes} bytes",
                    })
                    return
                try:
                    req = json.loads(self.rfile.read(length) or b"{}")
                except (ValueError, json.JSONDecodeError):
                    self._json(400, {"error": "malformed JSON body"})
                    return
                if not isinstance(req, dict):
                    self._json(400, {"error": "body must be a JSON object"})
                    return
                if self.path.startswith("/admin/"):
                    if not outer._admin_allowed(self):
                        self._json(403, {"error": "admin endpoint: loopback "
                                                  "or bearer token required"})
                        return
                    if self.path == "/admin/brownout":
                        self._json(*outer._admin_brownout(req))
                    else:
                        self._json(*outer._admin_reload(req))
                else:
                    outer._generate(self, req)

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    def _bump(self, key: str, n: int = 1) -> None:
        with self._stats_lock:
            self.stats[key] += n

    # ------------------------------------------------------------- lifecycle

    def start(self, probe: bool = True) -> None:
        if probe and not self._probe_thread.ident:
            self._probe_thread.start()
        if probe and self._obs_enabled() and not self._obs_thread.ident:
            self._obs_thread.start()
        if self._autoscale_enabled() and not self._autoscale_thread.ident:
            self._autoscale_thread.start()
        self._server_thread = threading.Thread(
            target=self._httpd.serve_forever, name="router-http", daemon=True
        )
        self._server_thread.start()

    def serve_forever(self) -> None:
        if not self._probe_thread.ident:
            self._probe_thread.start()
        if self._obs_enabled() and not self._obs_thread.ident:
            self._obs_thread.start()
        if self._autoscale_enabled() and not self._autoscale_thread.ident:
            self._autoscale_thread.start()
        try:
            self._httpd.serve_forever()
        finally:
            self.stop()

    def stop(self) -> None:
        self._stop.set()
        self._httpd.shutdown()
        self._httpd.server_close()  # release the listening socket

    def wait_ready(self, timeout: float = 10.0) -> bool:
        """Block until at least one replica is routable (first probes have
        landed) or the timeout expires."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self.registry.routable():
                return True
            time.sleep(0.01)
        return bool(self.registry.routable())

    # --------------------------------------------------------------- probing

    def _probe_loop(self) -> None:
        tick = min(self.registry.probe_interval / 4.0, 0.05)
        while not self._stop.wait(tick):
            for rep in self.registry.due():
                if self._stop.is_set():
                    return
                self.probe_once(rep.id)

    def probe_once(self, rid: str) -> bool:
        """One /healthz probe of one replica; folds the outcome into the
        registry and surfaces ejection/recovery events."""
        rep = self.registry.get(rid)
        self._bump("probes")
        ok, code, body = False, None, None
        conn = None
        t0 = self.clock()
        try:
            conn = http.client.HTTPConnection(
                rep.host, rep.port, timeout=self.probe_timeout
            )
            conn.request("GET", "/healthz")
            resp = conn.getresponse()
            code = resp.status
            body = json.loads(resp.read() or b"{}")
            ok = isinstance(body, dict)
        except (OSError, ValueError, http.client.HTTPException):
            ok = False
        finally:
            if conn is not None:
                conn.close()
        t1 = self.clock()
        if not ok:
            self._bump("probe_failures")
        self._registry_events(
            self.registry.observe_probe(rid, ok, code, body,
                                        rtt_window=(t0, t1))
        )
        return ok

    def _registry_events(self, events: List[Tuple[str, str]]) -> None:
        for name, rid in events:
            if name == "ejected":
                self._bump("ejections")
                self.affinity.forget_replica(rid)
                with self._tenant_aff_lock:
                    for t in [
                        t for t, r in self._tenant_affinity.items()
                        if r == rid
                    ]:
                        del self._tenant_affinity[t]
                self.flight.event("replica_ejected", replica=rid)
                # the post-mortem window: what the fleet looked like when
                # the replica dropped out (probe history, relay counters)
                self.flight.dump(
                    f"replica_ejected_{rid.replace(':', '_')}",
                    extra={"replica": rid, "registry": self.registry.snapshot()},
                )
            elif name == "recovered":
                self._bump("recoveries")
                self.flight.event("replica_recovered", replica=rid)

    # ---------------------------------------------- fleet observability plane

    def _obs_enabled(self) -> bool:
        return self.metrics_scrape_interval > 0

    def _obs_loop(self) -> None:
        """Scrape every routable replica's /metrics into the aggregator,
        then evaluate the SLO engine over the fresh rollups — one loop so
        an evaluation never reads half-updated aggregates."""
        last_eval = 0.0
        while not self._stop.wait(self.metrics_scrape_interval):
            try:
                self.scrape_fleet_metrics()
                now = self.clock()
                if self.slo is not None and (
                    now - last_eval >= self.slo_eval_interval
                ):
                    last_eval = now
                    self.brownout_tick(self.evaluate_slo())
            except Exception:  # noqa: BLE001 — the obs loop must outlive any one bad scrape
                self.flight.event("obs_loop_error")

    def scrape_fleet_metrics(self) -> int:
        """One aggregation pass: GET /metrics (Prometheus text) from every
        routable replica, fold into the aggregator, and drop replicas that
        left the registry. Returns how many scrapes landed."""
        live = {r.id: r for r in self.registry.routable()}
        for rid in self.aggregator.replicas():
            if rid not in self.registry.replicas:
                self.aggregator.drop(rid)
        landed = 0
        for rid, rep in live.items():
            conn = None
            try:
                conn = http.client.HTTPConnection(
                    rep.host, rep.port, timeout=self.probe_timeout
                )
                conn.request(
                    "GET", "/metrics?format=prometheus",
                    headers={"Accept": "text/plain;version=0.0.4"},
                )
                resp = conn.getresponse()
                text = resp.read().decode("utf-8", "replace")
                if resp.status == 200:
                    self.aggregator.update(rid, rep.role, text)
                    landed += 1
            except (OSError, http.client.HTTPException):
                pass  # probe failures own reachability; a missed scrape just ages the rollup
            finally:
                if conn is not None:
                    conn.close()
        if landed:
            self._bump("metrics_scrapes", landed)
        return landed

    def _build_slo(self, spec) -> Optional[SLOEngine]:
        """The SLO engine from declared objectives: None/default list,
        dicts (config file shape), or ready Objective instances. An empty
        sequence disables SLO evaluation."""
        if spec is None:
            objectives = default_objectives()
        elif isinstance(spec, dict):
            # config-file shape: {"qos": ..., "brownout": ..., "objectives":
            # [...]} — the qos/brownout blocks were consumed in __init__
            objectives = parse_slo_config(spec)
            if not objectives:
                return None
        elif not spec:
            return None
        elif all(isinstance(o, Objective) for o in spec):
            objectives = list(spec)
        else:
            objectives = parse_slo_config(list(spec))
        engine = SLOEngine(clock=self.clock)
        for obj in objectives:
            engine.add_objective(obj, self._bind_slo_source(obj))
        engine.on_fast_burn(self._on_slo_fast_burn)
        return engine

    def _bind_slo_source(self, obj: Objective):
        """(bad, total) cumulative source for one declared metric: latency
        objectives read the fleet-merged histograms (aggregated streams),
        availability and dropped_streams read the router's own counters."""
        # a qos_class binds the objective to that class's OWN histogram
        # stream (``serve_ttft_seconds_gold``) — the engine emits one
        # family per declared class, and the aggregator merges any family
        # name, so a per-class objective needs no aggregator changes
        suffix = f"_{obj.qos_class}" if obj.qos_class else ""
        if obj.metric == "ttft_p99":
            return lambda: self._latency_source(
                f"serve_ttft_seconds{suffix}", obj.threshold_s
            )
        if obj.metric == "itl_p99":
            return lambda: self._latency_source(
                f"serve_itl_seconds{suffix}", obj.threshold_s
            )
        if obj.metric == "availability":
            def availability():
                with self._stats_lock:
                    total = self.stats["requests"]
                    bad = self.stats["rejected_no_replica"]
                return (bad, total)
            return availability
        if obj.metric == "dropped_streams":
            def dropped():
                with self._stats_lock:
                    return (self.stats["dropped_streams"],
                            max(1, self.stats["streams"]))
            return dropped
        raise ValueError(f"no source for SLO metric {obj.metric!r}")

    def _latency_source(self, family: str, threshold_s: float):
        gt = self.aggregator.good_total_below(family, threshold_s)
        if gt is None:
            return None  # no replica scrape yet; the objective waits
        good, total = gt
        return (total - good, total)

    def evaluate_slo(self) -> Dict[str, Any]:
        """One SLO evaluation over the current aggregates (the obs loop's
        cadence; tests call it directly). Returns the /slo payload."""
        if self.slo is None:
            return {"objectives": {}, "verdict": "disabled", "evaluated": 0,
                    "window_clipped": True}
        self._bump("slo_evaluations")
        return self.slo.evaluate()

    def slo_snapshot(self) -> Dict[str, Any]:
        if self.slo is None:
            return {"objectives": {}, "verdict": "disabled", "evaluated": 0,
                    "window_clipped": True}
        return self.slo.snapshot()

    def _on_slo_fast_burn(self, obj: Objective, snap: Dict[str, Any]) -> None:
        """Fast burn = the error budget dies in hours: fire the EXISTING
        machinery — a flight-recorder dump with the fleet snapshot (the
        3am post-mortem), an event the autoscaler consumes as an up-signal
        on its next tick, and the engine's own loud log."""
        self._bump("slo_fast_burns")
        with self._slo_lock:
            self._slo_hot = True
        self.flight.event("slo_fast_burn", objective=obj.name, **{
            k: v for k, v in snap.items() if not isinstance(v, dict)
        })
        self.flight.dump(
            f"slo_fast_burn_{obj.name}",
            extra={
                "objective": obj.name,
                "snapshot": snap,
                "registry": self.registry.snapshot(),
                "slo": self.slo.snapshot() if self.slo else {},
            },
        )

    def consume_slo_hot(self) -> bool:
        """Autoscaler side of the up-signal: reads AND clears the flag so
        one burn episode contributes one round of up-pressure."""
        with self._slo_lock:
            hot, self._slo_hot = self._slo_hot, False
        return hot

    # ------------------------------------------------ fleet brownout control

    def _brownout_hot(self, evaluation: Dict[str, Any]) -> bool:
        """One evaluation's verdict for the brownout ladder: a PROTECTED
        class's own objective is burning fast or violated. Fleet-wide
        (classless) objectives feed the autoscaler, not the ladder — the
        ladder exists to sacrifice batch for gold, and only a per-class
        signal says WHO is hurting."""
        for snap in (evaluation.get("objectives") or {}).values():
            if (
                snap.get("qos_class") in self._brownout_protected
                and snap.get("state") in ("fast_burn", "violated")
            ):
                return True
        return False

    def brownout_tick(self, evaluation: Dict[str, Any]) -> None:
        """One controller step, driven by the obs loop right after each
        SLO evaluation (tests call it directly with a synthetic payload).
        Escalations and reverts both propagate to every routable replica;
        a non-normal rung is also re-asserted each tick so a replica that
        restarted (back at ``normal``) reconverges without an event."""
        transition = self.brownout.observe(self._brownout_hot(evaluation))
        if transition is not None:
            old, new = transition
            self._bump("brownout_transitions")
            self.flight.event("fleet_brownout", old=old, new=new,
                              rung_index=self.brownout.rung_index)
            if rung_at_least(new, "shrink_batch") and not rung_at_least(
                old, "shrink_batch"
            ):
                # crossing into actively degrading batch output is the
                # post-mortem-worthy moment — dump the fleet state once
                self.flight.dump(f"fleet_brownout_{new}", extra={
                    "old": old, "new": new,
                    "registry": self.registry.snapshot(),
                    "slo": self.slo.snapshot() if self.slo else {},
                })
        if transition is not None or self.brownout.rung_index > 0:
            self._push_brownout(self.brownout.rung)

    def _push_brownout(self, rung: str) -> None:
        """POST the current rung to every routable replica (idempotent on
        the replica side). A replica that misses the push converges on the
        next tick; an unreachable one is the probe loop's problem."""
        for rep in self.registry.routable():
            try:
                self._post_replica(
                    rep, "/admin/brownout", {"rung": rung},
                    timeout=self.probe_timeout,
                )
            except (OSError, http.client.HTTPException):
                pass

    def _admin_brownout(self, req: dict):
        """(code, body) for POST /admin/brownout on the ROUTER: operator
        override of the fleet rung (``{"rung": "normal"}`` clears it).
        The forced rung propagates immediately; the controller keeps
        running from there, so sustained calm still walks it back."""
        rung = req.get("rung")
        if not isinstance(rung, str):
            return 400, {"error": "rung must be a string"}
        try:
            transition = self.brownout.force(rung)
        except ValueError as exc:
            return 400, {"error": str(exc)}
        if transition is not None:
            old, new = transition
            self._bump("brownout_transitions")
            self.flight.event("fleet_brownout_forced", old=old, new=new)
        self._push_brownout(self.brownout.rung)
        return 200, self.brownout.snapshot()

    def _on_tenant_evicted(self, tenant: str) -> None:
        """TenantLedger capacity-eviction honesty (PR 18 satellite): a
        dropped rollup row is a billing gap — count it and leave a
        flight-recorder breadcrumb naming the tenant."""
        self._bump("tenant_ledger_evictions")
        self.flight.event("tenant_ledger_evicted", tenant=tenant)

    # ---- cross-process trace stitching

    def fetch_replica_spans(
        self, rep: Replica, request_id: Optional[str] = None,
    ) -> Optional[Dict[str, Any]]:
        """One replica's span tail (GET /admin/spans) — None when the
        replica is unreachable or does not serve spans (a stub fleet
        member mid-upgrade): stitching degrades to fewer tracks, never
        fails the request."""
        conn = None
        try:
            conn = http.client.HTTPConnection(
                rep.host, rep.port, timeout=self.probe_timeout
            )
            path = "/admin/spans"
            if request_id:
                path += f"?request_id={request_id}"
            conn.request("GET", path)
            resp = conn.getresponse()
            body = resp.read()
            if resp.status != 200:
                return None
            doc = json.loads(body or b"{}")
            return doc if isinstance(doc, dict) else None
        except (OSError, ValueError, http.client.HTTPException):
            return None
        finally:
            if conn is not None:
                conn.close()

    def merged_trace(self, request_id: Optional[str] = None) -> Dict[str, Any]:
        """ONE Perfetto document for a request (or the whole recent window
        with ``request_id=None``): the router's spans as the reference
        track plus every reachable replica's span tail, each replica's
        timestamps corrected by its probe-estimated clock offset onto the
        router clock, one pid per process. This is the artifact that makes
        a disaggregated request's latency readable — router, prefill,
        ship, decode, and attach hops on separate tracks of one timeline."""
        groups: List[Dict[str, Any]] = [{
            "process": "router",
            "offset_s": 0.0,
            "spans": self.tracer.track_dicts(track=request_id),
        }]
        for rep in list(self.registry.replicas.values()):
            doc = self.fetch_replica_spans(rep, request_id)
            if doc is None:
                continue
            spans = doc.get("spans") or []
            if not spans:
                continue
            groups.append({
                "process": f"{doc.get('role', rep.role)}:{rep.id}",
                "offset_s": rep.clock_offset_s,
                "spans": spans,
            })
        self._bump("stitched_traces")
        merged = stitch_spans(groups)
        if request_id:
            merged["otherData"]["request_id"] = request_id
            merged["otherData"]["stitch"] = verify_stitched(
                merged, request_id, slack_s=self._stitch_slack_s()
            )
        return merged

    def _stitch_slack_s(self) -> float:
        """Orphan/ordering tolerance for stitched traces: the clock-offset
        error bar is rtt/2 per replica — use the worst live estimate,
        floored at 50 ms (scheduler jitter on loaded boxes)."""
        rtts = [
            r.clock_rtt_s for r in self.registry.replicas.values()
            if r.clock_rtt_s != float("inf")
        ]
        return max(0.05, max(rtts) / 2.0 if rtts else 0.0)

    def export_merged_trace(
        self, path: str, request_id: Optional[str] = None,
    ) -> str:
        from zero_transformer_tpu.obs.fleet import write_trace

        return write_trace(path, self.merged_trace(request_id))

    def verify_run_traces(self) -> Dict[str, Any]:
        """Per-run stitched-trace verification: one merged doc for the
        whole recent window, then every request id with a ``route`` root
        checked for coverage / orphans / hop order. The loadgen embeds
        this block in BENCH_router.json."""
        doc = self.merged_trace()
        slack = self._stitch_slack_s()
        rids = request_ids_in(doc)
        checks = {
            rid: verify_stitched(doc, rid, slack_s=slack) for rid in rids
        }
        return {
            "requests": len(rids),
            "coverage_min": min(
                (c["coverage"] for c in checks.values()), default=0.0
            ),
            "orphans": sum(c["orphans"] for c in checks.values()),
            "hops_ordered": all(
                c["hops_ordered"] for c in checks.values()
            ) if checks else False,
            "per_request": checks,
        }

    def _admin_trace(self, query: str):
        """(code, body) for GET /admin/trace?request_id=<rid>: the merged
        fleet trace (Perfetto JSON) for one request, stitch verification
        included in otherData."""
        from urllib.parse import parse_qs

        rid = (parse_qs(query).get("request_id") or [None])[0]
        if not rid:
            return 400, {"error": "request_id is required"}
        return 200, self.merged_trace(_clean_rid(rid))

    # --------------------------------------------------------------- routing

    def _route(
        self, tokens: Optional[Sequence[int]], exclude: Set[str],
        tenant: Optional[str] = None,
    ) -> Optional[Replica]:
        # prefill-role replicas never take a whole request (their engine
        # rejects anything without a decode target) — the classic path and
        # the recompute fallback route only to decode-capable replicas
        candidates = [
            r for r in self.registry.routable()
            if r.id not in exclude and r.role != "prefill"
        ]
        chunk = self.affinity.chunk_tokens
        affine = tokens is not None and chunk >= 1 and len(tokens) >= chunk
        prefix_aff = self.affinity.lookup(tokens)
        # tenant affinity (PR 18): a tenant with no prefix match still
        # lands on its last replica — its per-tenant state there (prefix
        # cache, warm pages) keeps paying off, and a flooding tenant's
        # damage stays concentrated instead of smeared fleet-wide. Prefix
        # affinity is more specific and wins when both exist. The
        # anonymous pool is excluded: pinning all untagged traffic to one
        # replica would defeat least-loaded balancing.
        named = tenant is not None and tenant != "anon"
        tenant_aff = None
        aff = prefix_aff
        if aff is None and named:
            tenant_aff = self._tenant_affinity_lookup(tenant)
            aff = tenant_aff
        rep = pick_replica(candidates, aff)
        if rep is not None:
            if affine:
                if prefix_aff == rep.id:
                    self._bump("affinity_hits")
                else:
                    self._bump("affinity_misses")
                self.affinity.record(tokens, rep.id)
            if named:
                if tenant_aff is not None:
                    self._bump(
                        "tenant_affinity_hits" if tenant_aff == rep.id
                        else "tenant_affinity_misses"
                    )
                self._tenant_affinity_record(tenant, rep.id)
            self._bump("routed")
        return rep

    def _tenant_affinity_lookup(self, tenant: str) -> Optional[str]:
        with self._tenant_aff_lock:
            return self._tenant_affinity.get(tenant)

    def _tenant_affinity_record(self, tenant: str, rid: str) -> None:
        with self._tenant_aff_lock:
            self._tenant_affinity[tenant] = rid
            self._tenant_affinity.move_to_end(tenant)
            while len(self._tenant_affinity) > self._tenant_affinity_capacity:
                self._tenant_affinity.popitem(last=False)

    # ------------------------------------------- disaggregated dispatch

    def _disagg_enabled(self) -> bool:
        """True when the fleet can split a request by phase: at least one
        prefill-role replica AND one decode-capable one in rotation."""
        if self.disaggregate == "off":
            return False
        reps = self.registry.routable()
        return any(r.role == "prefill" for r in reps) and any(
            r.role != "prefill" for r in reps
        )

    def _plan_disagg(
        self, tokens: Optional[Sequence[int]]
    ) -> Optional[Tuple[Replica, Replica]]:
        """(prefill replica, decode replica) for a fresh request: admission
        is prefix-affine WITHIN the prefill pool (its chunk cache is what
        affinity is for); decode placement goes where the pages fit best —
        most free_pages, then lowest ITL EWMA (both scraped on /healthz)."""
        reps = self.registry.routable()
        prefills = [r for r in reps if r.role == "prefill"]
        # pages can only land on a paged-layout engine with a MATCHING
        # draft_k (prefill replicas never speculate, so their handoffs
        # carry draft_k 0): a slab or speculative replica in the fleet
        # must not silently turn every handoff into a failed ship +
        # recompute fallback
        decodes = [
            r for r in reps
            if r.role != "prefill" and r.importable and r.draft_k == 0
        ]
        if not prefills or not decodes:
            return None
        aff = self.affinity.lookup(tokens) if tokens is not None else None
        P = pick_replica(prefills, aff)
        D = pick_decode_replica(decodes)
        if P is None or D is None:
            return None
        return P, D

    def _replica_for_url(self, url: str) -> Replica:
        """The registry's replica for a ``migrated_to`` URL, or an ad-hoc
        row when the target is outside the registry (still relayed — the
        page shipper trusted it, so the attach must follow the pages)."""
        rid, host, port = _parse_url(url)
        rep = self.registry.replicas.get(rid)
        if rep is None:
            rep = Replica(id=rid, url=url, host=host, port=port, state=READY)
        return rep

    def _disagg_dispatch(
        self, P: Replica, D: Replica, req: dict, rid: str, state: dict,
    ) -> Tuple[bool, str]:
        """Phase 1 of the split request: a prefill-only JSON dispatch to
        ``P`` naming ``D`` as the page target. On success the stream's next
        hop is an ATTACH at the decode replica (``state['attach']``); any
        failure degrades to the classic path (False, reason)."""
        body = dict(req)
        body.pop("request_id", None)
        body["stream"] = False
        body["prefill_to"] = (
            D.url if "//" in D.url else f"http://{D.url}"
        )
        self.registry.inc_relay(P.id)
        hop0 = self.clock()
        hop_idx = state.get("hops", 0)
        state["hops"] = hop_idx + 1
        state.setdefault("replica_ids", []).append(P.id)
        status: Optional[int] = None
        try:
            status, doc = self._post_replica(P, "/generate", body, rid=rid,
                                             hop=hop_idx)
        except (OSError, http.client.HTTPException) as exc:
            self._registry_events(
                self.registry.observe_relay_failure(P.id, str(exc))
            )
            return False, f"prefill replica {P.id} failed: {exc}"
        finally:
            self.registry.dec_relay(P.id)
            self.tracer.add("relay", rid, hop0, self.clock(), {
                "replica": P.id, "mode": "prefill", "hop": hop_idx,
                "status": status if status is not None else "dead",
            })
        if status == 200 and doc.get("status") == "migrated" and doc.get(
            "migrated_to"
        ):
            if req.get("tokens") is not None:
                # prefill affinity: the NEXT prompt sharing this prefix
                # should land on the same prefill replica's chunk cache
                self.affinity.record(req["tokens"], P.id)
            state["attach"] = str(doc["migrated_to"])
            self._bump("disagg_dispatches")
            self._bump("routed")
            return True, ""
        return False, (
            f"prefill dispatch to {P.id} returned {status}: "
            f"{doc.get('error', doc.get('status', ''))}"
        )

    def _attach_collect(
        self, url: str, rid: str, hop: int = 0
    ) -> Tuple[List[int], Optional[dict]]:
        """Attach to an imported stream and collect it wholesale (the JSON
        non-stream path's tail of a migrated request)."""
        rep = self._replica_for_url(url)
        conn = None
        try:
            conn = self._connect(rep)
            conn.request(
                "POST", "/attach", json.dumps({"request_id": rid}),
                {"Content-Type": "application/json", "X-Request-Id": rid,
                 "X-Trace-Hop": str(hop)},
            )
            resp = conn.getresponse()
            if resp.status != 200:
                return [], None
            ids: List[int] = []
            texts: List[str] = []
            while True:
                line = resp.readline()
                if not line:
                    return ids, None
                if not line.startswith(b"data: "):
                    continue
                event = json.loads(line[6:])
                if event.get("done"):
                    event["text"] = "".join(texts) if texts else event.get(
                        "text", ""
                    )
                    return ids, event
                if "token" in event:
                    ids.append(int(event["token"]))
                if event.get("text"):
                    texts.append(str(event["text"]))
        except (OSError, ValueError, http.client.HTTPException):
            return [], None
        finally:
            if conn is not None:
                conn.close()

    # ---------------------------------------------------------------- health

    def _healthz(self):
        routable = self.registry.routable()
        alive = self._probe_thread.is_alive() or not self._probe_thread.ident
        ok = bool(routable) and alive
        return (200 if ok else 503), {
            "status": "ok" if ok else (
                "no_routable_replicas" if alive else "probe thread dead"
            ),
            "routable": len(routable),
            "replicas": self.registry.snapshot(),
            "rolling_reload_active": self._reload_busy.locked(),
            # fleet brownout state: visible on the same poll every LB and
            # operator already watches — rung changes are never silent
            "brownout_rung": self.brownout.rung,
            "brownout": self.brownout.snapshot(),
        }

    def _admin_allowed(self, handler) -> bool:
        peer = handler.client_address[0]
        if peer in ("127.0.0.1", "::1", "::ffff:127.0.0.1"):
            return True
        if self.admin_token:
            auth = handler.headers.get("Authorization", "")
            return auth == f"Bearer {self.admin_token}"
        return False

    # --------------------------------------------------------------- metrics

    def metrics_snapshot(self) -> Dict[str, Any]:
        with self._stats_lock:
            snap: Dict[str, Any] = dict(self.stats)
        aff_total = snap["affinity_hits"] + snap["affinity_misses"]
        snap["routable_replicas"] = len(self.registry.routable())
        snap["affinity_hit_rate"] = (
            snap["affinity_hits"] / aff_total if aff_total else 0.0
        )
        snap["replicas"] = self.registry.snapshot()
        snap["tenants"] = self.tenants.snapshot()
        snap["slo_verdict"] = (
            self.slo.snapshot()["verdict"] if self.slo is not None
            else "disabled"
        )
        snap["brownout_rung"] = self.brownout.rung
        snap["qos_classes"] = self.qos.snapshot()
        return snap

    def _register_exports(self) -> None:
        reg = self.metrics
        for key, help_text in (
            ("requests", "Requests received by the router"),
            ("tokens_relayed", "Tokens relayed to clients"),
            ("routed", "Routing decisions made"),
            ("retries", "Pre-stream retries (connect/5xx/backpressure)"),
            ("failovers", "Replica failovers (mid-stream + pre-stream)"),
            ("resumed_streams", "Streams resumed on a survivor mid-generation"),
            ("aborted_streams", "Streams terminated with a retryable error event"),
            ("dropped_streams", "Streams left without a terminal event (must stay 0)"),
            ("client_disconnects", "Client-side disconnects mid-stream"),
            ("rejected_no_replica", "Requests rejected: no routable replica"),
            ("affinity_hits", "Prefix-affinity routing hits"),
            ("affinity_misses", "Prefix-affinity routing misses"),
            ("probes", "Health probes sent"),
            ("probe_failures", "Health probes that failed"),
            ("ejections", "Replica ejections"),
            ("recoveries", "Replica recoveries after ejection"),
            ("rolling_reloads", "Rolling fleet reloads started"),
            ("reload_steps", "Per-replica rolling-reload steps completed"),
            ("reload_failures", "Per-replica rolling-reload steps failed"),
            ("disagg_dispatches", "Requests split prefill/decode by phase"),
            ("disagg_fallbacks", "Disagg dispatches degraded to the classic path"),
            ("migration_resumes", "Streams attach-resumed after a migration"),
            ("migrations_requested", "Streams asked to migrate (drain/retire)"),
            ("resume_replayed_tokens",
             "Tokens re-sent as prompt by the recompute fallback (attach adds 0)"),
            ("autoscale_ups", "Replicas spawned by the autoscaler"),
            ("autoscale_downs", "Replicas retired by the autoscaler"),
            ("autoscale_aborts", "Scale-downs aborted over undrainable streams"),
            ("metrics_scrapes", "Per-replica /metrics scrapes folded into the fleet rollups"),
            ("slo_evaluations", "SLO engine evaluation passes"),
            ("slo_fast_burns", "SLO fast-burn escalations fired"),
            ("stitched_traces", "Merged fleet traces assembled"),
            ("rejected_quota", "Requests rejected: fleet tenant quota"),
            ("rejected_brownout", "Requests rejected: fleet brownout"),
            ("brownout_transitions", "Fleet brownout rung transitions"),
            ("tenant_affinity_hits", "Tenant-affinity routing hits"),
            ("tenant_affinity_misses", "Tenant-affinity routing misses"),
            ("tenant_ledger_evictions",
             "Tenant rollup rows evicted at ledger capacity"),
        ):
            reg.counter_func(
                f"router_{key}", help_text, (lambda k=key: self.stats[k])
            )
        reg.gauge_func(
            "router_routable_replicas", "Replicas currently in rotation",
            lambda: len(self.registry.routable()),
        )
        reg.gauge_func(
            "router_brownout_rung",
            "Fleet brownout rung index (0=normal .. 3=suspend_batch)",
            lambda: self.brownout.rung_index,
        )
        # bounded-ring honesty, fleet-standard name (PR 15 satellite): the
        # router's own trace truncation is as silent-failure-prone as a
        # replica's
        reg.gauge_func(
            "obs_spans_dropped",
            "Spans dropped by ring overflow (trace truncation honesty)",
            lambda: self.tracer.dropped,
        )
        # SLO engine exposition: one labeled series per declared objective
        # (values read from the last evaluation — a scrape never triggers
        # an evaluation of its own)

        def slo_rows(field: str):
            if self.slo is None:
                return []
            snap = self.slo.snapshot()
            return [
                ({"objective": name}, obj[field])
                for name, obj in sorted(snap["objectives"].items())
            ]

        reg.gauge_func(
            "slo_budget_remaining",
            "Error budget remaining per objective (1 = untouched)",
            lambda: slo_rows("budget_remaining"),
        )
        reg.gauge_func(
            "slo_burn_rate_short",
            "Burn rate over the objective's short window",
            lambda: slo_rows("burn_rate_short"),
        )
        reg.gauge_func(
            "slo_burn_rate_long",
            "Burn rate over the objective's long window",
            lambda: slo_rows("burn_rate_long"),
        )
        reg.gauge_func(
            "slo_fast_burn",
            "1 while the objective is fast-burning",
            lambda: [
                (labels, 1 if state == "fast_burn" else 0)
                for labels, state in slo_rows("state")
            ],
        )
        reg.gauge_func(
            "slo_violated",
            "1 while any objective is burning or out of budget",
            lambda: (
                1 if self.slo is not None
                and self.slo.snapshot()["verdict"] == "violated" else 0
            ),
        )
        # per-tenant cost rollups (the capacity-planning scrape)
        for field, help_text in (
            ("requests", "Requests completed per tenant"),
            ("tokens_relayed", "Tokens relayed per tenant"),
            ("pages_held_ticks", "KV page x tick capacity consumed per tenant"),
            ("decode_ticks", "Decode ticks consumed per tenant"),
            ("migrations", "Stream migrations per tenant"),
        ):
            reg.counter_func(
                f"router_tenant_{field}", help_text,
                (lambda f=field: self.tenants.samples(f)),
            )
        # the four per-replica families share ONE registry snapshot per
        # scrape: render() calls the callbacks in registration order, so the
        # first (router_replica_up) refreshes the cell and the other three
        # reuse it — keep these four registrations together and in order
        snap_cell: Dict[str, Any] = {}

        def fleet(refresh: bool = False) -> Dict[str, Any]:
            if refresh or "snap" not in snap_cell:
                snap_cell["snap"] = self.registry.snapshot()
            return snap_cell["snap"]

        reg.gauge_func(
            "router_replica_up", "1 while the replica is in rotation",
            lambda: [
                ({"replica": rid}, 1 if info["state"] in (READY, DEGRADED)
                 and not info["cordoned"] else 0)
                for rid, info in fleet(refresh=True).items()
            ],
        )
        reg.gauge_func(
            "router_replica_queue_depth", "Scraped per-replica queue depth",
            lambda: [
                ({"replica": rid}, info["queue_depth"])
                for rid, info in fleet().items()
            ],
        )
        reg.gauge_func(
            "router_replica_active_relays",
            "Router-side in-flight relays per replica",
            lambda: [
                ({"replica": rid}, info["active_relays"])
                for rid, info in fleet().items()
            ],
        )
        reg.counter_func(
            "router_replica_tokens_relayed", "Tokens relayed per replica",
            lambda: [
                ({"replica": rid}, info["tokens_relayed"])
                for rid, info in fleet().items()
            ],
        )
        # engine page-pool stats mirrored fleet-wide (pre-PR12 free_pages
        # was a poll-only /healthz field; now every scrape of the router
        # shows per-replica KV pressure and migration load)
        reg.gauge_func(
            "router_replica_free_pages", "Scraped per-replica free KV pages",
            lambda: [
                ({"replica": rid}, info["free_pages"])
                for rid, info in fleet().items()
            ],
        )
        reg.counter_func(
            "router_replica_page_faults", "Scraped per-replica page faults",
            lambda: [
                ({"replica": rid}, info["page_faults"])
                for rid, info in fleet().items()
            ],
        )
        reg.counter_func(
            "router_replica_cow_copies",
            "Scraped per-replica copy-on-write page copies",
            lambda: [
                ({"replica": rid}, info["cow_copies"])
                for rid, info in fleet().items()
            ],
        )
        reg.gauge_func(
            "router_replica_migrations_in_flight",
            "Scraped per-replica in-flight page shipments",
            lambda: [
                ({"replica": rid}, info["migrations_in_flight"])
                for rid, info in fleet().items()
            ],
        )

    # ----------------------------------------------------------------- relay

    def _connect(self, rep: Replica) -> http.client.HTTPConnection:
        """Connect with the short connect timeout, then widen the socket
        timeout to the stream budget (a healthy replica may legitimately
        take longer between tokens than it may take to accept a TCP
        connection)."""
        conn = http.client.HTTPConnection(
            rep.host, rep.port, timeout=self.connect_timeout
        )
        conn.connect()
        conn.sock.settimeout(self.stream_timeout)
        return conn

    def _post_replica(
        self, rep: Replica, path: str, body: dict,
        rid: Optional[str] = None, timeout: Optional[float] = None,
        hop: Optional[int] = None,
    ) -> Tuple[int, dict]:
        """Small JSON POST helper (admin + probe paths, not the relay)."""
        conn = http.client.HTTPConnection(
            rep.host, rep.port, timeout=timeout or self.stream_timeout
        )
        try:
            headers = {"Content-Type": "application/json"}
            if rid:
                headers["X-Request-Id"] = rid
            if hop is not None:
                headers["X-Trace-Hop"] = str(hop)
            conn.request("POST", path, json.dumps(body), headers)
            resp = conn.getresponse()
            payload = resp.read()
            try:
                doc = json.loads(payload or b"{}")
            except ValueError:
                doc = {"error": "unparseable replica response"}
            # the replica advertises its backoff as an HTTP header, not a
            # body field — fold it in so _retry_after_of sees it
            ra = resp.getheader("Retry-After")
            if ra is not None and "retry_after" not in doc:
                doc["retry_after"] = ra
            return resp.status, doc
        finally:
            conn.close()

    def _generate(self, handler, req: dict) -> None:
        rid = _clean_rid(
            handler.headers.get("X-Request-Id") or req.get("request_id")
        )
        self._bump("requests")
        tokens = req.get("tokens")
        if tokens is not None:
            try:
                tokens = [int(t) for t in tokens]
                req = {**req, "tokens": tokens}
            except (TypeError, ValueError):
                self._bump("rejected_invalid")
                handler._json(400, {"error": "tokens must be integers",
                                    "request_id": rid},
                              headers={"X-Request-Id": rid})
                return
        # the numeric fields the ROUTER itself does arithmetic on (resume
        # budgets, deadline shrinking) must parse here: a malformed value
        # raising mid-relay would tear the connection with no response and
        # pollute dropped_streams — the counter the chaos proofs pin to 0
        try:
            req = {**req, "max_new_tokens": int(req.get("max_new_tokens", 32))}
            if "timeout" in req:
                req["timeout"] = float(req["timeout"])
        except (TypeError, ValueError):
            self._bump("rejected_invalid")
            handler._json(400, {
                "error": "max_new_tokens/timeout must be numeric",
                "request_id": rid,
            }, headers={"X-Request-Id": rid})
            return
        # tenant key for the cost-ledger rollup and the quota/affinity
        # planes (header wins over body field; absent traffic pools under
        # "anon"); the QoS class rides the same precedence, normalized so
        # an unknown class degrades to default service, never a 400
        tenant = str(
            handler.headers.get("X-Tenant-Key") or req.get("tenant") or "anon"
        )[:64]
        qos_name = self.qos.normalize(
            handler.headers.get("X-QoS-Class") or req.get("qos")
        )
        # tenant + class ride the relay BODY: _hop_body forwards dict(req)
        # verbatim, so the replica's own admission sees the same identity
        req = {**req, "tenant": tenant, "qos": qos_name}
        cls = self.qos.classes[qos_name]
        # fleet brownout, final rung: the lowest class is suspended at the
        # front door — no replica dispatch, class-aware Retry-After
        if rung_at_least(self.brownout.rung, "suspend_batch") and (
            self.qos.rank(qos_name) == len(self.qos.names()) - 1
        ):
            self._bump("rejected_brownout")
            handler._json(503, {
                "error": (
                    f"fleet brownout ({self.brownout.rung}): {qos_name} "
                    "admission suspended; retry later"
                ),
                "status": "rejected", "request_id": rid,
            }, headers={
                "Retry-After": str(max(1, math.ceil(cls.retry_after_s))),
                "X-Request-Id": rid,
            })
            return
        # fleet-level tenant quota: the per-class bucket scaled by current
        # routable capacity — one tenant's flood burns its own allotment
        # before any replica queue sees it
        quota_wait = self._fleet_buckets.take(
            tenant, qos_name,
            len(req.get("tokens") or ()) + int(req.get("max_new_tokens", 32)),
            self.clock(),
            scale=max(1, len(self.registry.routable())),
        )
        if quota_wait > 0:
            self._bump("rejected_quota")
            handler._json(429, {
                "error": (
                    f"tenant quota exhausted ({qos_name}); retry later"
                ),
                "status": "rejected", "request_id": rid,
            }, headers={
                "Retry-After": str(max(1, math.ceil(quota_wait))),
                "X-Request-Id": rid,
            })
            return
        if req.get("stream", True):
            self._bump("streams")
            state = {"ids": [], "texts": [], "terminal": False,
                     "headers_sent": False, "failover_count": 0,
                     "hops": 0, "replica_ids": [], "ledger": None,
                     "replayed": 0, "tenant": tenant}
            try:
                self._relay_stream(handler, req, rid, state)
            finally:
                if not state["terminal"]:
                    # every exit path above must have delivered a terminal
                    # event (done, error event, or observed client
                    # disconnect); anything else is a DROPPED stream — the
                    # counter the chaos proofs pin to zero
                    self._bump("dropped_streams")
        else:
            self._bump("json_requests")
            self._relay_json(handler, req, rid, tenant=tenant)

    # ---- JSON (non-stream) relay: nothing reaches the client until the
    # replica's full response is in hand, so every failure mode is a safe
    # wholesale retry on another replica.

    def _relay_json(self, handler, req: dict, rid: str,
                    tenant: str = "anon") -> None:
        t0 = self.clock()
        tried: Set[str] = set()
        retry_after = 1.0
        last_error = "no routable replica"
        hops = 0
        failovers = 0
        attach_hops = 0
        for attempt in range(self.max_attempts):
            rep = self._route(req.get("tokens"), tried,
                              tenant=req.get("tenant"))
            if rep is None:
                break
            tried.add(rep.id)
            self.registry.inc_relay(rep.id)
            hop0 = self.clock()
            hop_idx = hops
            hops += 1
            status, doc, dead = None, None, None
            try:
                code_doc = self._post_replica(rep, "/generate", req, rid=rid,
                                              hop=hop_idx)
                status, doc = code_doc
            except (OSError, http.client.HTTPException) as exc:
                dead = f"{type(exc).__name__}: {exc}"
            finally:
                self.registry.dec_relay(rep.id)
                self.tracer.add("relay", rid, hop0, self.clock(), {
                    "replica": rep.id, "mode": "json", "hop": hop_idx,
                    "status": status if status is not None else "dead",
                })
            if dead is not None:
                self._registry_events(
                    self.registry.observe_relay_failure(rep.id, dead)
                )
                self._bump("failovers")
                failovers += 1
                last_error = f"replica {rep.id} failed: {dead}"
                time.sleep(self.retry_backoff_s * (2 ** attempt))
                continue
            if status in (429, 503):
                retry_after = max(retry_after, _retry_after_of(doc))
                self._bump("retries")
                last_error = str(doc.get("error", f"replica {status}"))
                continue
            if status >= 500:
                # replica-side failure (500/502/504...): nothing reached the
                # client — retry elsewhere, with suspicion like a dead socket
                self._registry_events(
                    self.registry.observe_relay_failure(
                        rep.id, f"replica {status}"
                    )
                )
                self._bump("failovers")
                failovers += 1
                last_error = str(doc.get("error", f"replica {status}"))
                time.sleep(self.retry_backoff_s * (2 ** attempt))
                continue
            if status == 200 and doc.get("status") == "failed":
                # the replica admitted, then its engine failed the request
                # retryably (tick fault); nothing reached the client — retry
                self._bump("failovers")
                failovers += 1
                last_error = str(doc.get("error", "replica engine failure"))
                continue
            replicas_crossed = {rep.id}
            if status == 200 and doc.get("status") == "migrated" and doc.get(
                "migrated_to"
            ):
                # the stream moved mid-request (drain-as-migrate or a
                # disaggregated handoff): collect the continuation at its
                # new home — zero tokens replayed
                ids2, done2 = self._attach_collect(
                    doc["migrated_to"], rid, hop=hops
                )
                if done2 is None or done2.get("status") != "done":
                    self._bump("failovers")
                    failovers += 1
                    last_error = (
                        f"migrated stream lost at {doc['migrated_to']}"
                    )
                    continue
                self._bump("migration_resumes")
                attach_hops += 1
                replicas_crossed.add(_parse_url(doc["migrated_to"])[0])
                doc = {
                    "status": "done",
                    "tokens": (doc.get("tokens") or []) + ids2,
                    "text": (doc.get("text") or "") + str(
                        done2.get("text", "")
                    ),
                    # the attach hop's done event carries the CUMULATIVE
                    # engine ledger (it rode the page-span payload)
                    "ledger": done2.get("ledger", doc.get("ledger")),
                }
            n_tokens = len(doc.get("tokens") or ())
            self.registry.add_tokens(rep.id, n_tokens)
            self._bump("tokens_relayed", n_tokens)
            doc["request_id"] = rid
            doc["replica"] = rep.id
            doc["ledger"] = complete_ledger(
                doc.get("ledger"),
                replicas_crossed=len(replicas_crossed),
                failovers=failovers,
                attach_hops=attach_hops,
                resume_replayed_tokens=0,
                tokens_relayed=n_tokens,
                relay_ms=round((self.clock() - t0) * 1e3, 3),
            )
            self.tenants.record(tenant, doc["ledger"])
            self._finish_trace(rid, t0, doc.get("status", str(status)),
                               failovers=len(tried) - 1)
            handler._json(status, doc, headers={"X-Request-Id": rid})
            return
        self._bump("rejected_no_replica")
        self._finish_trace(rid, t0, "rejected", failovers=max(0, len(tried) - 1))
        handler._json(503, {
            "error": last_error, "status": "rejected", "request_id": rid,
        }, headers={
            "Retry-After": str(max(1, math.ceil(retry_after))),
            "X-Request-Id": rid,
        })

    # ---- SSE relay with mid-stream failover.

    def _relay_stream(self, handler, req: dict, rid: str, state: dict) -> None:
        t0 = self.clock()
        orig_tokens = req.get("tokens")
        max_new = int(req.get("max_new_tokens", 32))
        tried: Set[str] = set()
        retry_after = 1.0
        last_error = "no routable replica"
        attempt = 0
        disagg_tried = False
        # a pending attach always gets its hop: attach hops don't consume
        # the dispatch budget (they are migrations, not failures), so a
        # stream migrated on its FINAL permitted dispatch must still follow
        # its pages instead of dying "retry budget exhausted"
        while attempt < self.max_attempts or state.get("attach"):
            relayed = len(state["ids"])
            attach_to = state.pop("attach", None)
            if attach_to is not None:
                # zero-recompute hop: the stream's pages moved; follow them
                # with an attach (no prompt re-send, no token replay). A
                # ping-ponging fleet is bounded by the attach budget — past
                # it the recompute fallback takes over.
                state["attach_hops"] = state.get("attach_hops", 0) + 1
                if state["attach_hops"] > 2 * self.max_attempts:
                    # break to the terminal-error path below (headers are
                    # sent by now): falling into the recompute branch here
                    # would bypass its non-resumable-text-prompt guard
                    last_error = "attach budget exhausted (migration loop)"
                    break
                rep = self._replica_for_url(attach_to)
                hop_path = "/attach"
                body = {"request_id": rid}
            if attach_to is None:
                if (
                    not disagg_tried
                    and not tried
                    and not state["ids"]
                    and self._disagg_enabled()
                ):
                    # fresh request on a disaggregated fleet: split it —
                    # prefill at max batch on a prefill replica, pages
                    # shipped to the decode replica we name, then attach
                    disagg_tried = True
                    plan = self._plan_disagg(orig_tokens)
                    if plan is not None:
                        ok, why = self._disagg_dispatch(
                            plan[0], plan[1], req, rid, state
                        )
                        if ok:
                            continue  # attach hop next
                        last_error = why
                        self._bump("disagg_fallbacks")
                rep = self._route(orig_tokens, tried,
                                  tenant=req.get("tenant"))
                if rep is None:
                    break
                attempt += 1
                tried.add(rep.id)
                hop_path = "/generate"
                body = self._hop_body(req, state["ids"], self.clock() - t0)
                if relayed:
                    # the recompute fallback re-sends every relayed token
                    # as prompt — O(tokens) replay, the cost the attach
                    # path exists to avoid (and the counter the
                    # zero-replay proof pins)
                    self._bump("resume_replayed_tokens", relayed)
                    state["replayed"] = state.get("replayed", 0) + relayed
            self.registry.inc_relay(rep.id)
            hop0 = self.clock()
            hop_idx = state.get("hops", 0)
            state["hops"] = hop_idx + 1
            state.setdefault("replica_ids", []).append(rep.id)
            hop_tokens_before = relayed
            conn = None
            outcome, detail = "dead", "connect"
            finish_done = None
            abort_error = None
            try:
                try:
                    conn = self._connect(rep)
                    conn.request(
                        "POST", hop_path, json.dumps(body),
                        {"Content-Type": "application/json",
                         "X-Request-Id": rid,
                         "X-Trace-Hop": str(hop_idx)},
                    )
                    resp = conn.getresponse()
                except (OSError, http.client.HTTPException) as exc:
                    raise _HopDead(f"connect: {type(exc).__name__}: {exc}")
                if hop_path == "/attach":
                    if resp.status != 200:
                        # the imported stream is not there (ingest failed,
                        # got consumed, or the replica restarted):
                        # recompute fallback — with suspicion only for 5xx
                        # (a wedged handler must accrue ejection pressure;
                        # a clean 404 is just a miss)
                        resp.read()
                        outcome = (
                            "replica_5xx" if resp.status >= 500
                            else "attach_miss"
                        )
                        detail = str(resp.status)
                        raise _HopDead(
                            f"attach at {rep.id} returned {resp.status}"
                        )
                    # counted on attach SUCCESS (matching the JSON path's
                    # collect-then-count), not when the migrated done event
                    # was merely seen — an attach miss is a fallback, not
                    # a zero-replay resume
                    self._bump("migration_resumes")
                if resp.status != 200:
                    payload = resp.read()
                    try:
                        doc = json.loads(payload or b"{}")
                    except ValueError:
                        doc = {}
                    ra = resp.getheader("Retry-After")
                    if ra is not None and "retry_after" not in doc:
                        doc["retry_after"] = ra
                    if resp.status in (429, 503):
                        # backpressure/drain: honest retry elsewhere, the
                        # replica is alive — no suspicion, no failover count
                        retry_after = max(retry_after, _retry_after_of(doc))
                        self._bump("retries")
                        last_error = str(doc.get("error", f"replica {resp.status}"))
                        outcome, detail = "backpressure", str(resp.status)
                        continue
                    if resp.status >= 500:
                        # replica-side failure before any stream bytes
                        # (500/502/504...): silently try the next replica,
                        # with suspicion — repeated 5xx should eject
                        outcome, detail = "replica_5xx", str(resp.status)
                        raise _HopDead(
                            f"replica {resp.status}: "
                            f"{doc.get('error', 'server error')}"
                        )
                    # client error (400 etc): the request itself is bad —
                    # forward verbatim, no retry can fix it
                    outcome, detail = "client_error", str(resp.status)
                    if not state["headers_sent"]:
                        doc.setdefault("request_id", rid)
                        try:
                            handler._json(resp.status, doc,
                                          headers={"X-Request-Id": rid})
                        except (BrokenPipeError, ConnectionResetError,
                                OSError):
                            self._bump("client_disconnects")
                        state["terminal"] = True
                    else:
                        self._finish_stream(
                            handler, rid, state, t0, "failed",
                            str(doc.get("error", f"replica {resp.status}")),
                            retryable=False,
                        )
                    return
                if not state["headers_sent"]:
                    try:
                        handler.send_response(200)
                        handler.send_header(
                            "Content-Type", "text/event-stream"
                        )
                        handler.send_header("Cache-Control", "no-cache")
                        handler.send_header("X-Request-Id", rid)
                        handler.end_headers()
                    except (BrokenPipeError, ConnectionResetError, OSError):
                        # the client left while we were still setting up:
                        # an ordinary disconnect, not a dropped stream
                        self._bump("client_disconnects")
                        state["terminal"] = True
                        outcome, detail = "client_gone", "headers"
                        return
                    state["headers_sent"] = True
                kind, payload = self._pump_sse(resp, handler, state)
                if kind == "client_gone":
                    self._bump("client_disconnects")
                    state["terminal"] = True
                    outcome, detail = "client_gone", ""
                    return
                if kind == "done":
                    status = str(payload.get("status", "done"))
                    if payload.get("ledger") is not None:
                        # the engine's cumulative cost ledger for this
                        # stream (migration hops carry it forward, so the
                        # LAST done event always holds the full total)
                        state["ledger"] = payload["ledger"]
                    if status == "migrated" and payload.get("migrated_to"):
                        # the replica shipped this stream's pages (live
                        # migration / drain-as-migrate): follow them with
                        # an attach hop — zero tokens replayed (counted at
                        # attach success, not here)
                        state["attach"] = str(payload["migrated_to"])
                        outcome, detail = "migrated", state["attach"]
                        continue
                    if status == "failed" and payload.get("retryable", True):
                        # the replica's engine failed this request retryably
                        # (tick fault / poisoned slot): a clean SSE ending,
                        # but the generation is incomplete — fail over with
                        # what was already relayed
                        last_error = str(payload.get("error", "replica engine failure"))
                        outcome, detail = "engine_failed", last_error
                        raise _HopDead(last_error)
                    # finish AFTER the finally's bookkeeping: the terminal
                    # event is the client's cue that stats/spans are final
                    outcome, detail = "done", status
                    finish_done = (
                        status, payload.get("error"),
                        bool(payload.get("retryable", False)),
                    )
                else:
                    # kind == "dead": mid-stream death (EOF/reset/timeout/torn)
                    raise _HopDead(str(payload))
            except _HopDead as exc:
                last_error = str(exc)
                self._bump("failovers")
                state["failover_count"] += 1
                if outcome in ("dead", "replica_5xx"):
                    self._registry_events(
                        self.registry.observe_relay_failure(rep.id, last_error)
                    )
                if outcome == "dead":
                    # the survivor taking over also takes over the prefix
                    # (a 5xx answer means the replica — and its prefix
                    # cache — is still alive, so affinity stays)
                    self.affinity.forget_replica(rep.id)
                if state["ids"] and len(state["ids"]) >= max_new:
                    # died between its last token and the done event — the
                    # budget is spent, nothing left to resume: the client
                    # has the whole generation, so it IS done (via the
                    # post-finally finish, not here, so the dead hop's
                    # bookkeeping lands before the terminal write)
                    finish_done = ("done", None, False)
                elif state["ids"] and orig_tokens is None:
                    # non-resumable: the router cannot reconstruct the token
                    # prompt a text request was tokenized into, and tokens
                    # already reached the client — degrade gracefully into a
                    # retryable terminal error, never a hang (written after
                    # the finally's bookkeeping, like every terminal event)
                    abort_error = (
                        f"replica failed mid-stream and the text prompt is "
                        f"not resumable ({last_error})"
                    )
                else:
                    if state["ids"]:
                        # a resume hop is about to dispatch; it only counts
                        # as a resumed stream once a survivor actually
                        # completes it (see _finish_stream) — not on the
                        # attempt
                        state["was_resumed"] = True
                    time.sleep(self.retry_backoff_s * (2 ** (attempt - 1)))
                    continue
            finally:
                if conn is not None:
                    conn.close()
                self.registry.dec_relay(rep.id)
                hop_n = len(state["ids"]) - hop_tokens_before
                self.registry.add_tokens(rep.id, hop_n)
                self.tracer.add("relay", rid, hop0, self.clock(), {
                    "replica": rep.id, "tokens": hop_n, "hop": hop_idx,
                    "resumed": hop_tokens_before > 0,
                    "outcome": outcome, "detail": detail,
                })
            if finish_done is not None:
                self._finish_stream(
                    handler, rid, state, t0, finish_done[0], finish_done[1],
                    retryable=finish_done[2],
                )
                return
            if abort_error is not None:
                self._bump("aborted_streams")
                self._finish_stream(
                    handler, rid, state, t0, "failed", abort_error,
                    retryable=True,
                )
                return
        # retry budget exhausted / nothing routable
        if state["headers_sent"]:
            self._bump("aborted_streams")
            self._finish_stream(
                handler, rid, state, t0, "failed",
                f"retry budget exhausted: {last_error}", retryable=True,
            )
        else:
            self._bump("rejected_no_replica")
            state["terminal"] = True
            self._finish_trace(rid, t0, "rejected", 0)
            handler._json(503, {
                "error": last_error, "status": "rejected", "request_id": rid,
            }, headers={
                "Retry-After": str(max(1, math.ceil(retry_after))),
                "X-Request-Id": rid,
            })

    def _hop_body(
        self, req: dict, relayed: List[int], elapsed: float
    ) -> dict:
        """The request body for this hop: verbatim on the first dispatch; on
        a resume, prompt = original tokens + everything already relayed,
        budget reduced by the same amount (the seed rides along — greedy
        continues the exact trajectory, seeded sampling a consistent one),
        and any client deadline shrunk by the time already spent."""
        body = dict(req)
        body.pop("request_id", None)
        if relayed:
            body["tokens"] = list(req["tokens"]) + list(relayed)
            body.pop("prompt", None)
            body["max_new_tokens"] = (
                int(req.get("max_new_tokens", 32)) - len(relayed)
            )
        if "timeout" in req:
            body["timeout"] = max(0.05, float(req["timeout"]) - elapsed)
        return body

    def _pump_sse(self, resp, handler, state: dict):
        """Relay SSE events replica -> client until the done event, the
        stream dies, or the client leaves. Token events forward as raw bytes
        (one readline + one write per token); every forwarded token id is
        recorded in ``state`` — that record IS the resume point."""
        while True:
            try:
                line = resp.readline()
            except (OSError, http.client.HTTPException) as exc:
                return "dead", f"read: {type(exc).__name__}: {exc}"
            if not line:
                return "dead", "stream ended before the done event"
            if not line.strip():
                continue  # SSE event separator
            if not line.startswith(b"data: "):
                continue
            try:
                event = json.loads(line[6:])
            except ValueError:
                return "dead", "torn SSE event"
            if event.get("done"):
                return "done", event
            try:
                handler.wfile.write(line.rstrip(b"\r\n") + b"\n\n")
                handler.wfile.flush()
            except (BrokenPipeError, ConnectionResetError, OSError):
                return "client_gone", None
            if "token" in event:
                state["ids"].append(int(event["token"]))
                self._bump("tokens_relayed")
            if event.get("text"):
                state["texts"].append(str(event["text"]))

    def _finish_stream(
        self, handler, rid: str, state: dict, t0: float, status: str,
        error: Optional[str], retryable: bool = False,
    ) -> None:
        """The terminal SSE event is always ROUTER-built: accumulated text
        across every hop (a resumed stream's per-replica done event only
        knows its own segment), the failover count, and the correlation id."""
        event: Dict[str, Any] = {
            "done": True,
            "status": status,
            "text": "".join(state["texts"]),
            "request_id": rid,
            "failovers": state.get("failover_count", 0),
            # the complete per-request cost ledger: the engine's cumulative
            # counters (from the final hop's done event) + the fleet-side
            # fields only the router knows — also rolled up per tenant
            "ledger": complete_ledger(
                state.get("ledger"),
                replicas_crossed=len(set(state.get("replica_ids", []))),
                failovers=state.get("failover_count", 0),
                attach_hops=state.get("attach_hops", 0),
                resume_replayed_tokens=state.get("replayed", 0),
                tokens_relayed=len(state["ids"]),
                relay_ms=round((self.clock() - t0) * 1e3, 3),
            ),
        }
        if error:
            event["error"] = error
            event["retryable"] = retryable
        # bookkeeping BEFORE the terminal write: the done event is the
        # client's cue that the stream is settled, so a client that reads it
        # and immediately scrapes /metrics must see these counters landed
        if status == "done" and state.get("was_resumed"):
            # the survivor finished what a dead replica started: one resumed
            # stream, however many hops the failover chain crossed
            self._bump("resumed_streams")
        self.tenants.record(state.get("tenant", "anon"), event["ledger"])
        state["terminal"] = True
        self._finish_trace(rid, t0, status, event["failovers"])
        try:
            handler.wfile.write(b"data: " + json.dumps(event).encode() + b"\n\n")
            handler.wfile.flush()
        except (BrokenPipeError, ConnectionResetError, OSError):
            self._bump("client_disconnects")

    def _finish_trace(
        self, rid: str, t0: float, outcome: str, failovers: int
    ) -> None:
        if self.tracer.enabled:
            self.tracer.add("route", rid, t0, self.clock(), {
                "id": rid, "outcome": outcome, "failovers": failovers,
            })

    # --------------------------------------------------------- rolling reload

    def _admin_reload(self, req: dict):
        """(code, body) for POST /admin/reload on the ROUTER: a rolling
        fleet reload. 409 while one is already running."""
        if not self._reload_busy.acquire(blocking=False):
            return 409, {"error": "rolling reload already in progress"}
        try:
            ok, steps = self._rolling_reload(
                params_path=req.get("params"),
                drain_timeout_s=float(req.get("drain_timeout", 30.0)),
                ready_timeout_s=float(req.get("ready_timeout", 60.0)),
            )
            return (200 if ok else 502), {
                "reloaded": ok,
                "replicas": steps,
                "dropped_streams": self.stats["dropped_streams"],
            }
        finally:
            self._reload_busy.release()

    def rolling_reload(
        self,
        params_path: Optional[str] = None,
        drain_timeout_s: float = 30.0,
        ready_timeout_s: float = 60.0,
    ) -> Tuple[bool, List[Dict[str, Any]]]:
        """Public in-process entry (the HTTP handler and tests share it)."""
        if not self._reload_busy.acquire(blocking=False):
            raise RuntimeError("rolling reload already in progress")
        try:
            return self._rolling_reload(params_path, drain_timeout_s,
                                        ready_timeout_s)
        finally:
            self._reload_busy.release()

    def _rolling_reload(
        self,
        params_path: Optional[str],
        drain_timeout_s: float,
        ready_timeout_s: float,
    ) -> Tuple[bool, List[Dict[str, Any]]]:
        """One replica at a time: cordon -> drain the router's in-flight
        relays to it -> replica /admin/reload -> wait READY -> uncordon.
        The fleet always keeps N-1 replicas taking traffic, and no stream
        is ever cut: new requests route around the cordoned replica while
        its in-flight ones finish at their own pace."""
        self._bump("rolling_reloads")
        self.flight.event("rolling_reload_begin", params=params_path or "")
        results: List[Dict[str, Any]] = []
        all_ok = True
        for rid in list(self.registry.replicas):
            rep = self.registry.get(rid)
            if rep.state == EJECTED:
                results.append({"replica": rid, "ok": False,
                                "error": "ejected; nothing to reload"})
                all_ok = False
                continue
            step: Dict[str, Any] = {"replica": rid, "ok": False}
            t0 = self.clock()
            self.registry.cordon(rid)
            try:
                migrated = self._migrate_off(rep)
                if migrated:
                    step["migrated_streams"] = migrated
                if not self._await_zero_relays(rid, drain_timeout_s):
                    step["error"] = (
                        f"drain timeout: {rep.active_relays} relays still "
                        f"in flight after {drain_timeout_s}s"
                    )
                    all_ok = False
                    results.append(step)
                    continue
                drained_at = self.clock()
                self.tracer.add("reload_drain", "router", t0, drained_at,
                                {"replica": rid})
                try:
                    code, doc = self._post_replica(
                        rep, "/admin/reload",
                        {"params": params_path} if params_path else {},
                    )
                except (OSError, http.client.HTTPException) as exc:
                    code, doc = 0, {"error": f"{type(exc).__name__}: {exc}"}
                if code != 200:
                    step["error"] = (
                        f"replica reload returned {code}: "
                        f"{doc.get('error', '')}"
                    )
                    self._bump("reload_failures")
                    all_ok = False
                    results.append(step)
                    continue
                if not self._await_ready(rid, ready_timeout_s):
                    step["error"] = f"not READY within {ready_timeout_s}s"
                    self._bump("reload_failures")
                    all_ok = False
                    results.append(step)
                    continue
                self.tracer.add("reload_swap", "router", drained_at,
                                self.clock(), {"replica": rid})
                # its prefix cache flushed on reload: old affinities point
                # at K/V that no longer exists
                self.affinity.forget_replica(rid)
                self._bump("reload_steps")
                self.flight.event("rolling_reload_step", replica=rid,
                                  reloads=doc.get("reloads"))
                step.update(ok=True, reloads=doc.get("reloads"),
                            drained_s=round(drained_at - t0, 3))
                results.append(step)
            finally:
                self.registry.uncordon(rid)
        self.flight.event("rolling_reload_end", ok=all_ok)
        return all_ok, results

    def _migrate_off(self, rep: Replica) -> int:
        """Drain-as-migrate: ask a cordoned replica to ship every live
        stream to the best surviving decode-capable replica. Cost O(pages)
        per stream instead of O(remaining tokens) of waiting; the open
        relays see ``migrated`` done events and attach-resume at the
        target. Best-effort: on any failure the classic wait-out drain
        still runs (and mid-stream death still has the recompute path)."""
        if not self.migrate_drain:
            return 0
        target = pick_decode_replica([
            r for r in self.registry.routable()
            if r.id != rep.id and r.role != "prefill" and r.importable
            and r.draft_k == rep.draft_k
        ])
        if target is None:
            return 0
        target_url = (
            target.url if "//" in target.url else f"http://{target.url}"
        )
        try:
            code, doc = self._post_replica(
                rep, "/admin/migrate_all", {"target": target_url},
                timeout=5.0,
            )
        except (OSError, http.client.HTTPException):
            return 0
        if code != 202:
            return 0
        n = int(doc.get("requested", 0) or 0)
        if n:
            self._bump("migrations_requested", n)
            self.flight.event(
                "drain_migrate", replica=rep.id, target=target.id, streams=n,
            )
        return n

    # ------------------------------------------------------------ autoscaler

    def _autoscale_enabled(self) -> bool:
        return self.autoscale_interval > 0 and self.scaler is not None

    def _autoscale_loop(self) -> None:
        while not self._stop.wait(self.autoscale_interval):
            try:
                self._autoscale_tick()
            except Exception as exc:  # noqa: BLE001 — the control loop must outlive any one bad decision
                self.flight.event("autoscale_error", error=repr(exc))

    def _load_signals(self) -> Dict[str, Any]:
        reps = self.registry.routable()
        return {
            "routable": len(reps),
            "total": len(self.registry),
            "queued": sum(r.queue_depth for r in reps),
            "active": sum(r.active_slots + r.active_relays for r in reps),
            "max_itl_ewma_ms": max(
                (r.itl_ewma_ms for r in reps), default=0.0
            ),
            "min_free_pages": min((r.free_pages for r in reps), default=0),
        }

    def _autoscale_tick(self) -> None:
        """One control-loop decision over the signals every probe already
        scrapes. Deliberately hysteretic: ``scale_patience`` consecutive
        breaches before acting, and up-pressure always resets the idle
        streak (flapping costs replica churn AND migrations)."""
        sig = self._load_signals()
        n = sig["routable"]
        if n == 0:
            return  # nothing routable is an outage, not a scaling problem
        slo_hot = self.consume_slo_hot()
        if slo_hot:
            sig["slo_fast_burn"] = True
        brownout_hot = self.brownout.rung_index > 0
        if brownout_hot:
            sig["brownout_rung"] = self.brownout.rung
        hot = (
            sig["queued"] / n >= self.scale_up_queue
            or (
                self.scale_up_itl_ms > 0
                and sig["max_itl_ewma_ms"] >= self.scale_up_itl_ms
            )
            or (
                self.scale_up_free_pages > 0
                and sig["min_free_pages"] < self.scale_up_free_pages
            )
            # the SLO engine's fast-burn up-signal: the declared objective
            # is dying faster than its budget — capacity now, diagnose later
            or slo_hot
            # an engaged brownout is the fleet ALREADY degrading service:
            # capacity is the fix, degradation is the stopgap
            or brownout_hot
        )
        idle = (
            sig["queued"] == 0 and sig["active"] <= self.scale_down_active
        )
        if hot and sig["total"] < self.max_replicas:
            self._idle_ticks = 0
            self._hot_ticks += 1
            if self._hot_ticks >= self.scale_patience:
                self._hot_ticks = 0
                self._scale_up(sig)
        elif idle and sig["total"] > self.min_replicas:
            self._hot_ticks = 0
            self._idle_ticks += 1
            if self._idle_ticks >= self.scale_patience:
                self._idle_ticks = 0
                self._scale_down(sig)
        else:
            self._hot_ticks = self._idle_ticks = 0

    def _scale_up(self, sig: Dict[str, Any]) -> None:
        try:
            url = self.scaler.spawn()
        except Exception as exc:  # noqa: BLE001 — a failed spawn is an event, not a router crash
            self.flight.event("autoscale_spawn_failed", error=repr(exc))
            return
        if not url:
            self.flight.event("autoscale_spawn_failed", error="no url")
            return
        rid = self.registry.add(url)
        self._bump("autoscale_ups")
        # the decision and its inputs, post-hoc diagnosable (obs satellite)
        self.flight.event("autoscale_up", replica=rid, **sig)

    def _pick_retire_victim(self) -> Optional[Replica]:
        """Least-loaded routable replica that the fleet can lose: never the
        last decode-capable replica, never the last prefill replica while
        disaggregation is serving."""
        reps = self.registry.routable()
        decodes = [r for r in reps if r.role != "prefill"]
        prefills = [r for r in reps if r.role == "prefill"]
        candidates = []
        for r in reps:
            if r.role == "prefill" and len(prefills) <= 1 and decodes:
                continue  # keep the disaggregated split alive
            if r.role != "prefill" and len(decodes) <= 1:
                continue  # never retire the last decode-capable replica
            candidates.append(r)
        if not candidates:
            return None
        return min(candidates, key=Replica.load_score)

    def _scale_down(self, sig: Dict[str, Any]) -> None:
        victim = self._pick_retire_victim()
        if victim is None:
            return
        rid = victim.id
        self.registry.cordon(rid)
        try:
            migrated = self._migrate_off(victim)
            if not self._await_zero_relays(rid, self.scale_drain_timeout_s):
                # live streams that could not move: abort the scale-down —
                # capacity is cheaper than a dropped stream
                self._bump("autoscale_aborts")
                self.flight.event(
                    "autoscale_down_aborted", replica=rid,
                    active_relays=self.registry.get(rid).active_relays,
                    **sig,
                )
                self.registry.uncordon(rid)
                return
        except Exception as exc:  # noqa: BLE001 — an aborted retire must leave the replica serving
            self.flight.event("autoscale_error", error=repr(exc))
            self.registry.uncordon(rid)
            return
        try:
            self.scaler.retire(victim.url)
        except Exception as exc:  # noqa: BLE001 — retire-hook failures are the operator's event to act on
            self.flight.event(
                "autoscale_retire_failed", replica=rid, error=repr(exc)
            )
        self.registry.remove(rid)
        self.affinity.forget_replica(rid)
        self._bump("autoscale_downs")
        self.flight.event(
            "autoscale_down", replica=rid, migrated=migrated, **sig
        )

    def _await_zero_relays(self, rid: str, timeout_s: float) -> bool:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if self.registry.get(rid).active_relays == 0:
                return True
            time.sleep(0.01)
        return self.registry.get(rid).active_relays == 0

    def _await_ready(self, rid: str, timeout_s: float) -> bool:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            self.probe_once(rid)
            if self.registry.get(rid).state == READY:
                return True
            time.sleep(0.05)
        return False

    # ----------------------------------------------------------------- misc

    def export_trace(self, path: str) -> str:
        return self.tracer.write_chrome_trace(path)


def _retry_after_of(doc: dict) -> float:
    try:
        return float(doc.get("retry_after", 1.0) or 1.0)
    except (TypeError, ValueError):
        return 1.0


def run_router(
    replicas: Sequence[str],
    host: str = "127.0.0.1",
    port: int = 8080,
    background: bool = False,
    **kwargs,
) -> Optional[RouterServer]:
    """Start the fleet router. ``background=True`` returns the running
    router (tests); otherwise blocks until interrupted."""
    router = RouterServer(replicas, host=host, port=port, **kwargs)
    if background:
        router.start()
        return router
    import signal

    def on_term(signum, frame):
        threading.Thread(target=router.stop, daemon=True).start()

    signal.signal(signal.SIGTERM, on_term)
    print(
        f"routing on http://{host}:{router.port} over "
        f"{len(router.registry)} replicas — POST /generate, GET /healthz, "
        "GET /metrics (JSON; Prometheus via Accept: text/plain), "
        "POST /admin/reload (rolling fleet reload)",
        flush=True,
    )
    router.serve_forever()
    return None
