"""Serving-side resilience: the PR-2 fault-tolerance discipline for serving.

``resilience/`` hardened *training* against its environment (in-graph anomaly
guard, supervisor, watchdog, chaos harness); this module is the serving
counterpart, reusing those primitives instead of duplicating them:

- ``Lifecycle``: an explicit engine state machine (STARTING -> READY ->
  DEGRADED -> DRAINING -> STOPPED) that ``/healthz`` reflects with real
  status codes, so a load balancer can route around a replica that is
  warming up, sick, or draining;
- ``CircuitBreaker``: consecutive decode-tick-fault counter; at the
  threshold the engine goes DEGRADED and rebuilds its jitted step (the
  serving analogue of the supervisor's bounded-restart loop — bounded here
  by ``max_rebuilds``);
- ``ItlEwma``: the measured inter-token-latency EWMA that deadline-aware
  load shedding prices admission against (the serving analogue of
  ``anomaly.py``'s running EMAs);
- ``validate_reload``: eval_shape-style structure/shape/dtype validation of
  a standby param tree before a hot swap (a corrupt or mismatched artifact
  is rejected with the engine staying READY on the old weights);
- ``ServingChaosMonkey``: the serving extension of ``resilience.chaos`` —
  decode-fault windows, NaN-logit injection (detected by the same
  non-finite criterion as the training guard, ``anomaly.nonfinite_rows``),
  slow ticks, mid-load SIGTERM, corrupt-reload artifacts — proving all of
  the above in ``tests/test_serving_resilience.py`` (``make serve-chaos``).

Host-side only: nothing here adds device work beyond one [S]-bool
non-finite reduction per tick, fetched in the same device_get as the
sampled tokens.
"""
from __future__ import annotations

import dataclasses
import os
import signal
import threading
import time
from typing import Any, Callable, List, Optional, Sequence, Tuple

import jax

from zero_transformer_tpu.resilience.chaos import ChaosMonkey, Fault

# ----------------------------------------------------------------- lifecycle

STARTING = "starting"  # constructed; scheduler loop not yet running
READY = "ready"  # serving; /healthz 200
DEGRADED = "degraded"  # breaker open after consecutive tick faults; rebuilt
DRAINING = "draining"  # admission closed; finishing in-flight generations
STOPPED = "stopped"  # terminal: drained, aborted, or stop()ed

_STATES = (STARTING, READY, DEGRADED, DRAINING, STOPPED)


class Lifecycle:
    """Thread-safe engine state machine with a transition history.

    Legal moves: STARTING -> {READY, DRAINING, STOPPED}; READY <-> DEGRADED;
    any live state -> DRAINING; DRAINING -> STOPPED only (a draining engine
    never goes back to taking traffic — restart it instead); STOPPED is
    terminal. Illegal transitions are refused (return False), not raised:
    callers race (tick thread vs signal handler vs HTTP thread) and the
    first writer wins.
    """

    def __init__(self, clock: Callable[[], float] = time.monotonic):
        self._clock = clock
        self._lock = threading.Lock()
        self._state = STARTING
        self._born = clock()
        self.history: List[Tuple[str, float, str]] = [(STARTING, self._born, "init")]

    @property
    def state(self) -> str:
        return self._state

    @property
    def uptime_s(self) -> float:
        return self._clock() - self._born

    def to(self, state: str, reason: str = "") -> bool:
        assert state in _STATES, state
        with self._lock:
            cur = self._state
            if state == cur or cur == STOPPED:
                return False
            if cur == DRAINING and state != STOPPED:
                return False
            if state == DEGRADED and cur not in (READY, STARTING):
                return False
            self._state = state
            self.history.append((state, self._clock(), reason))
            return True


# ------------------------------------------------------------ circuit breaker


class CircuitBreaker:
    """Consecutive-tick-fault breaker.

    ``record_fault`` returns True on the fault that OPENS the breaker (the
    engine's cue to go DEGRADED and rebuild); ``record_clean`` returns True
    on the clean tick that CLOSES it again (back to READY). ``cooldown``
    clean ticks are required to close — one by default: a rebuilt engine
    that survives a full fused tick has proven the executable.
    """

    def __init__(self, threshold: int = 3, cooldown: int = 1):
        if threshold < 1 or cooldown < 1:
            raise ValueError("threshold and cooldown must be >= 1")
        self.threshold = threshold
        self.cooldown = cooldown
        self.open = False
        self.consecutive_faults = 0
        self.trips = 0
        self._clean_streak = 0

    def record_fault(self) -> bool:
        self.consecutive_faults += 1
        self._clean_streak = 0
        # trip on EVERY threshold-multiple of the unbroken fault streak, not
        # only the first: an already-open breaker whose rebuilt engine keeps
        # faulting must keep tripping, or the rebuild budget (max_rebuilds)
        # can never exhaust and a structural fault spins forever
        if self.consecutive_faults % self.threshold == 0:
            self.open = True
            self.trips += 1
            return True
        return False

    def record_clean(self) -> bool:
        self.consecutive_faults = 0
        if not self.open:
            return False
        self._clean_streak += 1
        if self._clean_streak >= self.cooldown:
            self.open = False
            self._clean_streak = 0
            return True
        return False


# -------------------------------------------------------------- load shedding


class ItlEwma:
    """Measured inter-token latency EWMA (host side, one update per sample).

    ``floor_s`` is the conservative read shedding uses: admission must
    reject only PROVABLY infeasible deadlines, so the estimate is clamped
    from below by the fastest recent tick rather than inflated by a safety
    factor — overload degrades into honest 503s, never into shedding
    requests that would have made it.
    """

    def __init__(self, decay: float = 0.9, warmup: int = 8):
        self.decay = decay
        self.warmup = warmup
        self.value: Optional[float] = None
        self.count = 0
        self._min = float("inf")

    def update(self, sample: float) -> None:
        self.count += 1
        self._min = min(self._min, sample)
        if self.value is None:
            self.value = sample
        else:
            self.value = self.decay * self.value + (1.0 - self.decay) * sample

    @property
    def warm(self) -> bool:
        return self.count >= self.warmup and self.value is not None

    def floor_s(self) -> float:
        return min(self.value, self._min) if self.value is not None else 0.0


def infeasible_deadline(
    deadline: float,
    now: float,
    max_new_tokens: int,
    queue_depth: int,
    n_slots: int,
    itl: ItlEwma,
) -> bool:
    """True when ``deadline`` cannot be met even under best-case scheduling.

    Lower bound on completion: the request must decode ``max_new_tokens``
    ticks at no less than the fastest recently measured ITL, and it cannot
    start before the queue ahead of it has pushed at least
    ``queue_depth / n_slots`` tick-slots through the engine. No safety
    margin — a shed must be provable, not probable. Inert until the EWMA
    has ``warmup`` samples (a cold engine has no evidence to shed on).
    """
    if not itl.warm:
        return False
    tick = itl.floor_s()
    lower_bound = tick * (max_new_tokens + queue_depth / max(1, n_slots))
    return now + lower_bound > deadline


# ----------------------------------------------------------------- hot reload


class ReloadError(RuntimeError):
    """A standby param tree failed validation (corrupt artifact, wrong
    model); the engine stays READY on the old weights."""


def validate_reload(current: Any, candidate: Any) -> None:
    """Reject a candidate param tree whose structure, shapes, or dtypes
    differ from the serving tree (``jax.eval_shape``-level check: metadata
    only, nothing materializes). Raises ``ReloadError`` naming the first
    mismatch.

    Boxing-agnostic: a tree straight from ``Transformer.init`` carries flax
    ``Partitioned`` metadata boxes while a msgpack restore is plain — both
    describe the same weights, so both sides are unboxed before comparison.
    """
    try:
        from flax import linen as nn

        cur = jax.tree_util.tree_flatten_with_path(nn.meta.unbox(current))
        new = jax.tree_util.tree_flatten_with_path(nn.meta.unbox(candidate))
    except Exception as exc:  # not even a pytree of arrays
        raise ReloadError(f"unreadable param tree: {exc!r}") from exc
    (cur_leaves, cur_def), (new_leaves, new_def) = cur, new
    if cur_def != new_def:
        raise ReloadError(
            f"param tree structure mismatch: serving {cur_def} vs reload {new_def}"
        )
    for (path, a), (_, b) in zip(cur_leaves, new_leaves):
        a_shape, b_shape = getattr(a, "shape", None), getattr(b, "shape", None)
        a_dtype, b_dtype = getattr(a, "dtype", None), getattr(b, "dtype", None)
        if a_shape != b_shape or a_dtype != b_dtype:
            raise ReloadError(
                f"param leaf {jax.tree_util.keystr(path)} mismatch: serving "
                f"{a_shape}/{a_dtype} vs reload {b_shape}/{b_dtype}"
            )


# --------------------------------------------------------------- serving chaos


@dataclasses.dataclass
class ServeFault(Fault):
    """A serving fault (extends the training ``Fault``).

    kind: "tick_fault" | "prefill_fault" | "nan_logits" | "slow_tick" |
          "sigterm" | "corrupt_reload" | "slow_client"
    step: the scheduler TICK index the fault keys on (engine ``_tick``,
      0-based) — sigterm/slow_tick fire once at the first tick >= step;
      tick_fault / prefill_fault / nan_logits fire for ``duration``
      consecutive ticks. A prefill_fault raises inside the CHUNK-prefill
      dispatch (before the fused decode), proving the engine fails only
      the mid-prefill slots and leaves decoding neighbors untouched.
      "slow_client" is a CONSUMER fault: the server's SSE pump stalls for
      ``duration`` seconds mid-stream (a reader that stopped draining its
      socket), proving the bounded emit buffer finishes the stalled
      stream retryably while neighbors stay byte-identical; ``step`` here
      is the number of events the pump delivers before stalling.
    slots: for "nan_logits", which cache rows to poison (None = every
      occupied row) — how the harness proves the guard retires ONLY the
      affected slots.
    """

    slots: Optional[Sequence[int]] = None


class ServingChaosMonkey(ChaosMonkey):
    """Fault plan for the serving engine (reuses ChaosMonkey's fired-log /
    one-shot bookkeeping). Injection points mirror where real serving
    faults enter:

    - ``on_tick``: host-side, called at the top of every supervised tick —
      raises (a poisoned decode tick), sleeps (a stalled device / GC pause),
      or SIGTERMs this process (preemption mid-load);
    - ``poison_logits``: NaN rows written into the POST-step logits, so the
      non-finite guard sees injected NaNs through the exact path a real
      numerical blow-up takes;
    - ``corrupt_reload``: mangles a standby param tree between load and
      validation, proving a bad artifact is rejected with the engine READY.
    """

    def on_tick(self, tick: int) -> None:
        for f in self._of_kind("slow_tick"):
            if not f.fired and tick >= f.step:
                self.record(f)
                time.sleep(float(f.duration))
        for f in self._of_kind("sigterm"):
            if not f.fired and tick >= f.step:
                self.record(f)
                os.kill(os.getpid(), signal.SIGTERM)
        for f in self._of_kind("tick_fault"):
            if f.step <= tick < f.step + int(f.duration):
                if not f.fired:
                    self.record(f)
                raise f.exc(f"{f.message} (decode tick {tick})")

    def client_stall_s(self, events_delivered: int) -> float:
        """SSE-pump seam ("slow_client"): called by the server's stream
        pump after each delivered event; returns the seconds the pump
        should stall (simulating a reader that stopped draining) once
        ``events_delivered`` reaches the fault's ``step``. One-shot."""
        stall = 0.0
        for f in self._of_kind("slow_client"):
            if not f.fired and events_delivered >= f.step:
                self.record(f)
                stall += float(f.duration)
        return stall

    def on_prefill_chunk(self, tick: int) -> None:
        """Called at the top of a supervised chunk-prefill dispatch: a
        "prefill_fault" in its window raises here, through the exact path
        a real mid-chunk blow-up (OOM, bad artifact math) takes."""
        for f in self._of_kind("prefill_fault"):
            if f.step <= tick < f.step + int(f.duration):
                if not f.fired:
                    self.record(f)
                raise f.exc(f"{f.message} (prefill chunk, tick {tick})")

    def poison_logits(self, tick: int, logits):
        import jax.numpy as jnp

        for f in self._of_kind("nan_logits"):
            if f.step <= tick < f.step + int(f.duration):
                if not f.fired:
                    self.record(f)
                rows = (
                    list(f.slots)
                    if f.slots is not None
                    else list(range(logits.shape[0]))
                )
                logits = logits.at[jnp.asarray(rows, jnp.int32)].set(jnp.nan)
        return logits

    def corrupt_reload(self, tree):
        faults = self._of_kind("corrupt_reload")
        if not any(not f.fired for f in faults):
            return tree
        for f in faults:
            if not f.fired:
                self.record(f)
                break
        import jax.numpy as jnp

        # truncate the first leaf: exactly what a half-written msgpack looks
        # like after flax restores it — wrong shape, same tree
        leaves, treedef = jax.tree_util.tree_flatten(tree)
        first = leaves[0]
        leaves[0] = jnp.zeros((1,) * max(1, first.ndim), first.dtype)
        return jax.tree_util.tree_unflatten(treedef, leaves)
