"""Committed-prefix incremental detokenization.

Streaming text out of a byte-level BPE safely needs two guarantees the naive
``decode(all_tokens_so_far)`` loop does not give:

- O(n) total work: only the UNCOMMITTED tail is re-decoded each step (the
  HF ``TextStreamer`` pattern), not the whole sequence per token;
- no replacement chars mid-stream: a character whose bytes span tokens
  decodes to U+FFFD until complete, so output is held back while the tail is
  an incomplete byte sequence, and the concatenation of emitted pieces is
  byte-identical to the one-shot decode.

Extracted from ``serve.TextGenerator.stream`` so the SSE server and the REPL
stream through ONE implementation (the two surfaces must never diverge on
detok behavior).
"""
from __future__ import annotations

from typing import List, Optional


def decode_tokens(tokenizer, toks) -> str:
    """Detokenize WITHOUT clean_up_tokenization_spaces: the cleanup pass
    rewrites across token boundaries (" n" + "'t" -> "n't"), so a chunked
    streaming decode would diverge from the whole-sequence decode unless
    both paths pin it off. Falls back for tokenizers without the kwarg.
    Stateless — the one pinned decode for every surface (one-shot, REPL
    stream, SSE server)."""
    try:
        return tokenizer.decode(toks, clean_up_tokenization_spaces=False)
    except TypeError:
        return tokenizer.decode(toks)


class StreamDecoder:
    """Feed token ids, get decoded text increments."""

    def __init__(self, tokenizer):
        self.tokenizer = tokenizer
        self._pending: List[int] = []

    def decode(self, toks) -> str:
        return decode_tokens(self.tokenizer, toks)

    def push(self, token: int) -> Optional[str]:
        """Add one token; returns the next committed text piece, or None
        while the tail is an incomplete multi-byte character."""
        self._pending.append(token)
        text = self.decode(self._pending)
        if text.endswith("�"):
            return None
        self._pending = []
        return text

    def flush(self) -> Optional[str]:
        """Emit whatever is held back (a genuinely incomplete tail at stream
        end decodes with its replacement char)."""
        if not self._pending:
            return None
        text = self.decode(self._pending)
        self._pending = []
        return text
