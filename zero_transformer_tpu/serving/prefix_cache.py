"""Chunk-aligned token-prefix K/V cache (vLLM-style block hashing).

Shared-prefix traffic — N personas behind one system prompt, retried
requests, agent loops replaying a conversation head — re-pays prefill for
token spans whose K/V the engine has already computed. This LRU lets a new
prompt skip straight to its first novel chunk:

- **Key scheme**: an entry covers ONE chunk of ``chunk_tokens`` tokens and
  is keyed by the ENTIRE token prefix up to and including that chunk
  (``tuple(prompt[:j * chunk])``), not by the chunk's own tokens — K/V at a
  position depends on every earlier token, so two prompts may share chunk
  *contents* but never chunk *K/V* unless the whole prefix matches. This is
  exactly vLLM's prefix/block hash. Exact tuple keys (not a digest) mean a
  hash collision can never serve wrong K/V.
- **Value**: the per-layer K/V span for that chunk's positions
  (``SlotKVCache.extract_span`` — int8 scale leaves included), copied OUT
  of a slot row when a prefill completes and back IN on a later hit.
  Deterministic forward ⇒ reused spans are bit-identical to recomputation,
  so prefix hits preserve the engine's byte-identical parity contract.
- **Hit walk**: ``lookup`` extends the match one chunk at a time and stops
  strictly BEFORE the prompt's final token (``j * chunk < len(prompt)``):
  the last chunk is always recomputed, because the admission needs the
  logits at ``true_len - 1`` and spans store K/V only.
- **Invalidation**: ``flush()`` on hot weight reload (new weights make
  every cached span stale) and on device-state rebuild after a tick fault
  (the buffers are suspect). The engine owns calling it.

Host-side bookkeeping only; the device copies happen in the engine's jitted
span ops. Not thread-safe by itself — only the scheduler tick thread touches
it (admission and completion both run inside ``step()``).
"""
from __future__ import annotations

from collections import OrderedDict
from typing import Any, Dict, List, Sequence, Tuple


class PrefixCache:
    """LRU of chunk-aligned prefix K/V spans.

    ``capacity`` counts CHUNK ENTRIES (each worth ``chunk_tokens`` cache
    positions of K/V per layer), so the device memory the cache pins is
    bounded at ``capacity * chunk_tokens`` positions regardless of how many
    distinct prompts pass through.
    """

    def __init__(self, chunk_tokens: int, capacity: int):
        if chunk_tokens < 1:
            raise ValueError("chunk_tokens must be >= 1")
        if capacity < 1:
            raise ValueError("capacity must be >= 1 (0 disables at the engine)")
        self.chunk_tokens = chunk_tokens
        self.capacity = capacity
        self._entries: "OrderedDict[Tuple[int, ...], Any]" = OrderedDict()
        # cached DEEPER chunks per entry: an entry with live children is
        # never evicted (its children would become unreachable dead weight —
        # the hit walk stops at the first absent chunk), so eviction takes
        # the least-recent LEAF instead
        self._children: Dict[Tuple[int, ...], int] = {}
        self.hits = 0
        self.misses = 0
        self.stores = 0
        self.evictions = 0

    def __len__(self) -> int:
        return len(self._entries)

    def _key(self, prompt: Sequence[int], j: int) -> Tuple[int, ...]:
        return tuple(prompt[: j * self.chunk_tokens])

    def _parent(self, key: Tuple[int, ...]) -> Tuple[int, ...]:
        return key[: len(key) - self.chunk_tokens]

    def _link(self, key: Tuple[int, ...]) -> None:
        # counted whether or not the parent is RESIDENT: the map answers
        # "how many cached entries extend this key by one chunk", so a
        # parent stored out of order (re-cached after its deeper chunk)
        # arrives already pinned by its resident children — no scan needed
        parent = self._parent(key)
        if parent:
            self._children[parent] = self._children.get(parent, 0) + 1

    def _unlink(self, key: Tuple[int, ...]) -> None:
        parent = self._parent(key)
        if parent:
            n = self._children.get(parent, 1) - 1
            if n:
                self._children[parent] = n
            else:
                self._children.pop(parent, None)

    def _evict_one(self):
        """Pop the least-recently-used LEAF entry (no cached deeper chunk
        depends on it). Evicting a mid-chain entry would orphan its
        descendants: still resident, never again reachable by the hit walk —
        the whole-prefix-eviction bug this ordering exists to fix."""
        victim = next(
            (k for k in self._entries if not self._children.get(k)),
            next(iter(self._entries)),  # cycle-free tree: always has a leaf
        )
        return self._pop_entry(victim)

    def _pop_entry(self, victim: Tuple[int, ...]):
        value = self._entries.pop(victim)
        self._unlink(victim)
        self.evictions += 1
        return victim, value

    def lookup(self, prompt: Sequence[int]) -> Tuple[int, List[Any]]:
        """Longest chunk-aligned cached prefix of ``prompt``.

        Returns ``(tokens_covered, spans)`` where ``spans[i]`` is chunk
        ``i+1``'s K/V span; every covered chunk counts a hit and every
        remaining chunk-aligned chunk (still ending before the final token)
        counts a miss. The walk stops at the first absent chunk — a cached
        DEEPER chunk is unusable without its predecessors' K/V in the row.
        """
        C = self.chunk_tokens
        fill, spans = self.walk(prompt)
        for j in range(1, len(spans) + 1):
            self._entries.move_to_end(self._key(prompt, j))
        self.hits += len(spans)
        j = len(spans) + 1
        while j * C < len(prompt):
            self.misses += 1
            j += 1
        return fill, spans

    def walk(self, prompt: Sequence[int]) -> Tuple[int, List[Any]]:
        """The hit walk WITHOUT stats or recency side effects — capacity
        planning (the paged admission sizes its page reservation before
        committing to the hit, and must not count the same hit twice)."""
        C = self.chunk_tokens
        vals: List[Any] = []
        j = 1
        while j * C < len(prompt):
            v = self._entries.get(self._key(prompt, j))
            if v is None:
                break
            vals.append(v)
            j += 1
        return len(vals) * C, vals

    def contains(self, prompt: Sequence[int], j: int) -> bool:
        return self._key(prompt, j) in self._entries

    def store(self, prompt: Sequence[int], j: int, span: Any) -> None:
        """Insert chunk ``j`` (1-based) of ``prompt``'s prefix; evicts LRU
        entries past capacity. Re-storing an existing key just refreshes
        its recency (the spans are bit-identical by construction)."""
        key = self._key(prompt, j)
        if key in self._entries:
            self._entries.move_to_end(key)
            return
        self._entries[key] = span
        self._link(key)
        self.stores += 1
        while len(self._entries) > self.capacity:
            self._evict_one()

    def flush(self) -> int:
        """Drop every entry (hot reload / device rebuild); returns how many."""
        n = len(self._entries)
        self._entries.clear()
        self._children.clear()
        return n

    def stats(self) -> Dict[str, float]:
        total = self.hits + self.misses
        return {
            "prefix_hits": self.hits,
            "prefix_misses": self.misses,
            "prefix_stores": self.stores,
            "prefix_evictions": self.evictions,
            "prefix_entries": len(self._entries),
            "prefix_hit_rate": (self.hits / total) if total else 0.0,
        }


class PagedPrefixIndex(PrefixCache):
    """Prefix cache over PAGE IDS (the paged-KV unification): an entry's
    value is the tuple of pool pages holding that chunk's K/V, not a copy
    of the bytes.

    - **store** records the pages (already refcount-bumped by
      ``PagedKVCache.bank``) — no extraction dispatch, no device copy;
    - **a hit** hands the pages to ``PagedKVCache.share``, which maps them
      into the new slot's block table and bumps refcounts — reuse without
      moving a byte;
    - **eviction / flush** drop the index's reference through the pool:
      a page still mapped by a live slot (or, impossible by key-scheme but
      guarded anyway, another entry) survives until its last reference —
      the refcount-aware eviction the slab-era LRU lacked;
    - **reclaim(n)** frees at least ``n`` pages for an allocation that
      found the pool exhausted, evicting least-recent leaf entries first —
      the page-fault path the engine counts.

    Same key scheme, hit walk, children-aware LRU order, and stats surface
    as ``PrefixCache``.
    """

    def __init__(self, chunk_tokens: int, capacity: int, pool):
        super().__init__(chunk_tokens, capacity)
        self._pool = pool

    def _evict_one(self):
        key, pages = super()._evict_one()
        self._pool.decref(pages)
        return key, pages

    def store_pages(self, prompt: Sequence[int], j: int, pages) -> None:
        """Insert chunk ``j``'s pages; a duplicate store returns the extra
        references immediately (one index hold per page, ever)."""
        key = self._key(prompt, j)
        if key in self._entries:
            self._entries.move_to_end(key)
            self._pool.decref(pages)  # bank() bumped; the entry already holds
            return
        self.store(prompt, j, tuple(pages))

    def reclaim(self, n_pages: int) -> int:
        """Evict entries until >= ``n_pages`` pages came FREE (refcount
        zero); returns pages freed. Only entries whose eviction actually
        frees something are touched — least-recent FREEABLE leaf first —
        and the walk stops when no leaf would free a page: evicting an
        entry whose pages a live slot still maps gains zero capacity, and
        wiping the hot shared-prefix set on a failed admission would turn
        one capacity miss into a hit-rate collapse."""
        freed = 0
        while freed < n_pages:
            victim = next(
                (
                    k
                    for k, pages in self._entries.items()
                    if not self._children.get(k)
                    and any(self._pool.refs[p] == 1 for p in pages)
                ),
                None,
            )
            if victim is None:
                break
            _, pages = self._pop_entry(victim)
            freed += self._pool.decref(pages)
        return freed

    def flush(self) -> int:
        for pages in self._entries.values():
            self._pool.decref(pages)
        return super().flush()
