"""Chunk-aligned token-prefix K/V cache (vLLM-style block hashing).

Shared-prefix traffic — N personas behind one system prompt, retried
requests, agent loops replaying a conversation head — re-pays prefill for
token spans whose K/V the engine has already computed. This LRU lets a new
prompt skip straight to its first novel chunk:

- **Key scheme**: an entry covers ONE chunk of ``chunk_tokens`` tokens and
  is keyed by the ENTIRE token prefix up to and including that chunk
  (``tuple(prompt[:j * chunk])``), not by the chunk's own tokens — K/V at a
  position depends on every earlier token, so two prompts may share chunk
  *contents* but never chunk *K/V* unless the whole prefix matches. This is
  exactly vLLM's prefix/block hash. Exact tuple keys (not a digest) mean a
  hash collision can never serve wrong K/V.
- **Value**: the per-layer K/V span for that chunk's positions
  (``SlotKVCache.extract_span`` — int8 scale leaves included), copied OUT
  of a slot row when a prefill completes and back IN on a later hit.
  Deterministic forward ⇒ reused spans are bit-identical to recomputation,
  so prefix hits preserve the engine's byte-identical parity contract.
- **Hit walk**: ``lookup`` extends the match one chunk at a time and stops
  strictly BEFORE the prompt's final token (``j * chunk < len(prompt)``):
  the last chunk is always recomputed, because the admission needs the
  logits at ``true_len - 1`` and spans store K/V only.
- **Invalidation**: ``flush()`` on hot weight reload (new weights make
  every cached span stale) and on device-state rebuild after a tick fault
  (the buffers are suspect). The engine owns calling it.

Host-side bookkeeping only; the device copies happen in the engine's jitted
span ops. Not thread-safe by itself — only the scheduler tick thread touches
it (admission and completion both run inside ``step()``).
"""
from __future__ import annotations

from collections import OrderedDict
from typing import Any, Dict, List, Sequence, Tuple


class PrefixCache:
    """LRU of chunk-aligned prefix K/V spans.

    ``capacity`` counts CHUNK ENTRIES (each worth ``chunk_tokens`` cache
    positions of K/V per layer), so the device memory the cache pins is
    bounded at ``capacity * chunk_tokens`` positions regardless of how many
    distinct prompts pass through.
    """

    def __init__(self, chunk_tokens: int, capacity: int):
        if chunk_tokens < 1:
            raise ValueError("chunk_tokens must be >= 1")
        if capacity < 1:
            raise ValueError("capacity must be >= 1 (0 disables at the engine)")
        self.chunk_tokens = chunk_tokens
        self.capacity = capacity
        self._entries: "OrderedDict[Tuple[int, ...], Any]" = OrderedDict()
        self.hits = 0
        self.misses = 0
        self.stores = 0
        self.evictions = 0

    def __len__(self) -> int:
        return len(self._entries)

    def _key(self, prompt: Sequence[int], j: int) -> Tuple[int, ...]:
        return tuple(prompt[: j * self.chunk_tokens])

    def lookup(self, prompt: Sequence[int]) -> Tuple[int, List[Any]]:
        """Longest chunk-aligned cached prefix of ``prompt``.

        Returns ``(tokens_covered, spans)`` where ``spans[i]`` is chunk
        ``i+1``'s K/V span; every covered chunk counts a hit and every
        remaining chunk-aligned chunk (still ending before the final token)
        counts a miss. The walk stops at the first absent chunk — a cached
        DEEPER chunk is unusable without its predecessors' K/V in the row.
        """
        C = self.chunk_tokens
        spans: List[Any] = []
        j = 1
        while j * C < len(prompt):
            span = self._entries.get(self._key(prompt, j))
            if span is None:
                break
            self._entries.move_to_end(self._key(prompt, j))
            spans.append(span)
            self.hits += 1
            j += 1
        while j * C < len(prompt):
            self.misses += 1
            j += 1
        return len(spans) * C, spans

    def contains(self, prompt: Sequence[int], j: int) -> bool:
        return self._key(prompt, j) in self._entries

    def store(self, prompt: Sequence[int], j: int, span: Any) -> None:
        """Insert chunk ``j`` (1-based) of ``prompt``'s prefix; evicts LRU
        entries past capacity. Re-storing an existing key just refreshes
        its recency (the spans are bit-identical by construction)."""
        key = self._key(prompt, j)
        if key in self._entries:
            self._entries.move_to_end(key)
            return
        self._entries[key] = span
        self.stores += 1
        while len(self._entries) > self.capacity:
            self._entries.popitem(last=False)
            self.evictions += 1

    def flush(self) -> int:
        """Drop every entry (hot reload / device rebuild); returns how many."""
        n = len(self._entries)
        self._entries.clear()
        return n

    def stats(self) -> Dict[str, float]:
        total = self.hits + self.misses
        return {
            "prefix_hits": self.hits,
            "prefix_misses": self.misses,
            "prefix_stores": self.stores,
            "prefix_evictions": self.evictions,
            "prefix_entries": len(self._entries),
            "prefix_hit_rate": (self.hits / total) if total else 0.0,
        }
