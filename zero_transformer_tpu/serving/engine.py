"""Continuous-batching scheduler + request lifecycle.

``ServingEngine`` turns the repo's single-request jitted decode path
(``inference/generate.py``) into a concurrent serving surface:

- requests queue behind a bounded admission queue (backpressure: a full
  queue REJECTS at submit time rather than stacking unbounded latency);
- free slots admit queued requests. With ``prefill_chunk > 0`` (the serving
  default) the prompt prefills CHUNKED: ``prefill_chunk`` tokens per tick,
  written directly into the slot's rows of the shared ``SlotKVCache`` by one
  fixed-shape ``[n_slots, chunk]`` program that advances EVERY mid-prefill
  slot at once — so a long prompt never stalls active streams for its full
  prefill (the Sarathi-Serve interleaving), multiple queued prompts prefill
  as one batch (admission is inherently batched), and there is no
  small-cache-then-insert copy or per-prompt-length compile. A chunk-aligned
  token-prefix LRU (``serving/prefix_cache.py``) lets repeated system
  prompts skip straight to the first novel chunk. ``prefill_chunk = 0``
  keeps the legacy one-shot path: the prompt prefills into a fresh
  single-row cache (padded to a power-of-two bucket, count-capped so
  diverse lengths cannot compile-storm the replica), then
  ``SlotKVCache.insert`` copies it into the slot;
- every ``step()`` runs ONE fused decode step across all slots — padded and
  masked so the compiled program is identical whatever the occupancy — then
  retires slots that hit EOS, their token budget, a deadline, or a
  cancellation. With ``draft_k > 0`` the step is the SPECULATIVE twin:
  ``draft_k`` host-proposed prompt-lookup drafts per slot verified in the
  same single forward, committing ``1 + n_acc`` tokens per tick (greedy ≡
  plain decode bit-for-bit; sampling via the standard rejection rule);
- with ``kv_layout="paged"`` (the serving default at the CLI) the K/V slab
  is replaced by a block-table paged pool (``slots.PagedKVCache``): KV HBM
  is ``page_pool_tokens`` positions regardless of slot count, admission
  reserves each request's worst case so capacity pressure queues instead
  of faulting, and prefix-cache hits map shared pages by refcount instead
  of copying spans;
- each request carries its OWN rng chain and repetition-penalty mask,
  threaded per-slot through the fused step, so its token trajectory is
  IDENTICAL to what single-request ``generate()`` produces with the same
  seed (tested byte-for-byte).

Everything device-side is shape-static: admissions and retirements never
recompile anything. The engine itself is synchronous (``step()``); a serving
front end drives it from a background thread (``run()``) and talks to it
through thread-safe ``submit()`` / ``RequestHandle``.

Resilience (``serving/resilience.py`` owns the primitives):

- the engine carries an explicit ``Lifecycle`` (STARTING -> READY ->
  DEGRADED -> DRAINING -> STOPPED) that ``/healthz`` reflects;
- the decode tick is SUPERVISED: an exception inside one tick fails only
  the slots it poisons (retryable error to those clients), and a circuit
  breaker trips the engine into DEGRADED and rebuilds the jitted step
  after ``breaker_threshold`` consecutive faults (bounded by
  ``max_rebuilds``, then the fault escalates out of ``run()``);
- a per-tick non-finite-logits guard (the training anomaly guard's
  predicate, ``resilience.anomaly.nonfinite_rows``) retires only affected
  slots;
- ``begin_drain`` stops admission (queued requests finish as retryable
  rejections), lets in-flight generations complete up to a deadline, then
  force-finishes — SIGTERM maps here;
- ``reload_params`` validates a standby tree off the tick thread and swaps
  it between ticks without dropping a slot;
- admission sheds requests whose deadline is provably infeasible given
  queue depth and the measured ITL EWMA (fast honest 503s, not timeout
  storms).

Observability (``obs/`` owns the primitives — docs/OBSERVABILITY.md):

- every request carries a REQUEST ID (client-supplied ``X-Request-Id`` or
  generated at admission) and emits a well-nested span tree —
  ``request`` ⊃ {``queue``, ``prefill``, ``decode``} — into the engine's
  ring-buffered ``Tracer`` when it reaches a terminal state, whatever that
  state is (done/shed/expired/cancelled/faulted). Per-tick phase spans
  (``prefill_chunk``, ``decode_step``, ``emit``) land on the ``engine``
  track, so a Perfetto view shows where each tick's milliseconds went;
- latency metrics live in fixed-bucket ``obs.Histogram``s
  (``serve_ttft_seconds`` etc.): ``metrics_snapshot()`` percentiles are
  O(buckets) bucket walks and ``prometheus_text()`` renders the text
  exposition — neither touches the scheduler lock (pre-PR7 every scrape
  sorted three 10k-sample deques under it);
- a ``FlightRecorder`` keeps the last N tick summaries/events in RAM and
  dumps them (spans included) on breaker-open, drain, and abort;
- ``request_profile(n)`` stages a ``jax.profiler`` capture of the next n
  ticks (``POST /admin/profile``), started/stopped by the tick thread only.
"""
from __future__ import annotations

import dataclasses
import functools
import itertools
import math
import queue as queue_mod
import re
import threading
import time
import uuid
from collections import deque
from pathlib import Path
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp

from zero_transformer_tpu.analysis.runtime import (
    CompileFamilyExceeded,
    bounded_dispatch,
)
from zero_transformer_tpu.config import resolve_dtype
from zero_transformer_tpu.obs import (
    LATENCY_BUCKETS,
    FlightRecorder,
    ProfileWindow,
    Registry,
    Tracer,
    hbm_device_stats,
)

from zero_transformer_tpu.inference.generate import (
    _in_mesh,
    decode_model,
    init_cache,
)
from zero_transformer_tpu.inference.sampling import (
    NEG_INF,
    SamplingConfig,
    process_logits,
    sample_token,
)
from zero_transformer_tpu.inference.speculative import ngram_propose
from zero_transformer_tpu.resilience.detect import nonfinite_rows
from zero_transformer_tpu.serving.prefix_cache import PagedPrefixIndex, PrefixCache
from zero_transformer_tpu.serving.qos import (
    BROWNOUT_RUNGS,
    ClassQueue,
    QosPolicy,
    TenantBuckets,
    reserved_above,
    rung_at_least,
)
from zero_transformer_tpu.serving.resilience import (
    DEGRADED,
    DRAINING,
    READY,
    STOPPED,
    CircuitBreaker,
    ItlEwma,
    Lifecycle,
    ReloadError,
    infeasible_deadline,
    validate_reload,
)
from zero_transformer_tpu.serving.slots import (
    INDEX_LEAVES,
    TABLE_LEAF,
    PagedKVCache,
    SlotKVCache,
    _leaf_name,
)

# characters stripped from client-supplied request ids (keep the usual
# trace-id alphabets: alnum plus - _ . : / =)
_RID_UNSAFE = re.compile(r"[^A-Za-z0-9._:/=-]")

# request terminal states
QUEUED = "queued"
RUNNING = "running"
DONE = "done"
CANCELLED = "cancelled"
EXPIRED = "expired"
REJECTED = "rejected"
FAILED = "failed"  # the ENGINE died, not the request
# the stream now lives on another replica (its pages shipped there); the
# handle's ``migrated_to`` names the new home — a router attaches there and
# the client's stream continues with ZERO recomputed tokens
MIGRATED = "migrated"

_FINISHED = (DONE, CANCELLED, EXPIRED, REJECTED, FAILED, MIGRATED)

# engine roles (disaggregated prefill/decode fleets): a PREFILL replica runs
# only chunked prefill at max batch and ships every finished stream's pages
# to the decode replica the request names (``prefill_to``); a DECODE replica
# serves imported streams (and plain requests, as the recompute fallback);
# MIXED is the classic single-replica behavior.
ROLES = ("mixed", "prefill", "decode")


@dataclasses.dataclass
class Request:
    """One generation request, in token-id space (detokenization is the
    front end's job — the engine is tokenizer-agnostic)."""

    prompt: Sequence[int]
    max_new_tokens: int
    seed: int = 0
    # absolute deadline on the engine's clock (``engine.now()``); None = no
    # deadline. Enforced both in the queue and mid-decode.
    deadline: Optional[float] = None
    # disaggregation: when set, the finished prefill's pages ship to this
    # replica URL instead of decoding here (required on prefill-role
    # engines; honored on mixed engines too)
    prefill_to: Optional[str] = None
    # overload isolation (PR 18): the billing identity and QoS class this
    # request admits under. Unknown classes normalize to the policy's
    # default at submit; "anon"/default is the full pre-QoS behavior.
    tenant: str = "anon"
    qos: str = "standard"


class RequestHandle:
    """Thread-safe view of a submitted request: token stream + final state."""

    def __init__(self, request: Request, rid: int, submitted_at: float,
                 request_id: Optional[str] = None):
        self.request = request
        self.id = rid
        # correlation id: client-supplied (X-Request-Id) or generated —
        # returned in the response header and the SSE done event, and the
        # TRACK key of this request's span tree. SANITIZED to a safe header
        # charset: the value is echoed verbatim into a response header, so
        # CR/LF would let a client inject arbitrary headers (response
        # splitting) and non-latin-1 would crash send_header mid-response;
        # a client id that sanitizes to nothing falls back to a generated one
        if request_id:
            clean = _RID_UNSAFE.sub("", str(request_id))[:128]
            self.rid = clean or uuid.uuid4().hex
        else:
            self.rid = uuid.uuid4().hex
        self.submitted_at = submitted_at
        self.status = QUEUED
        self.tokens: List[int] = []
        self.first_token_at: Optional[float] = None
        self.finished_at: Optional[float] = None
        self.error: Optional[str] = None
        # retryable=True marks a failure/rejection the CLIENT should retry
        # (tick fault, drain, shed, breaker) — the server maps it to 503 +
        # Retry-After; invalid requests stay non-retryable 400s
        self.retryable = False
        self.retry_after: Optional[float] = None
        # terminal status ``migrated``: the replica URL now serving this
        # stream (the router attaches there and continues the client's SSE
        # with zero token replay)
        self.migrated_to: Optional[str] = None
        # how many prompt tokens a prefix-cache hit covered at admission
        # (0 = cold/miss/disabled) — the loadgen splits TTFT by this
        self.prefix_hit_tokens = 0
        # when the request left the queue for a slot: first_token_at minus
        # this is the prefill+first-decode latency the ENGINE controls
        # (TTFT minus queue wait), the clean denominator for prefix-cache
        # attribution under load
        self.admitted_at: Optional[float] = None
        # when the prompt's K/V finished landing in the slot (install into
        # the decode set) — the prefill/decode span boundary
        self.prefill_done_at: Optional[float] = None
        # the engine's Tracer; the lifecycle span tree is emitted from the
        # timestamps above in ONE batch at _finish (zero per-token cost)
        self._tracer: Optional[Tracer] = None
        # per-request cost ledger (obs/fleet.py PR 15): plain-int counters
        # the tick thread bumps — prefill chunks, decode ticks, drafted/
        # accepted tokens, pages held x ticks. Rides the page-span payload
        # on migration so the counts stay CUMULATIVE across replicas; the
        # ms split is computed from the lifecycle timestamps at read time,
        # with _ledger_ms_base carrying the milliseconds already spent on
        # earlier hops of a migrated stream.
        self.ledger: Dict[str, int] = {
            "prefill_chunks": 0, "decode_ticks": 0, "tokens_out": 0,
            "draft_tokens": 0, "accepted_tokens": 0, "pages_held_ticks": 0,
            "migrations": 0,
        }
        self._ledger_ms_base = {"queue_ms": 0.0, "prefill_ms": 0.0,
                                "decode_ms": 0.0}
        # propagated trace context: the router's hop index for this
        # dispatch (span attrs carry it so the stitched fleet trace can
        # assert hop ordering across processes)
        self.trace_hop: Optional[int] = None
        self._events: queue_mod.Queue = queue_mod.Queue()
        self._done = threading.Event()
        self._cancel = threading.Event()
        # bounded emit buffer (slow-client protection): once a STREAMING
        # consumer has attached (the server's SSE pump sets
        # consumer_attached) and stops draining, token events past
        # emit_buffer_max are dropped and ``overflowed`` trips — the
        # scheduler then finishes the stream retryably instead of holding
        # its slot/pages for a reader that went away. Non-streaming
        # waiters (result()) never attach, so their buffering stays
        # bounded by max_new_tokens exactly as before. The terminal
        # ("done", status) event is NEVER dropped.
        self.emit_buffer_max: int = 1024
        self.consumer_attached = False
        self.overflowed = False

    # -- consumer side -----------------------------------------------------

    def cancel(self) -> None:
        """Ask the scheduler to drop this request (queued or mid-decode).
        Takes effect at the next tick boundary; the handle finishes with
        status ``cancelled``."""
        self._cancel.set()

    def next_event(self, timeout: Optional[float] = None):
        """Blocking pop of the next ``("token", id)`` / ``("done", status)``
        event, or None when ``timeout`` elapses first (lets a server poll
        client liveness between events without killing the stream)."""
        try:
            return self._events.get(timeout=timeout)
        except queue_mod.Empty:
            return None

    def stream(self, timeout: Optional[float] = None):
        """Yield token ids as they generate; returns when the request
        reaches a terminal state. ``timeout`` bounds the wait per token
        (TimeoutError, same contract as ``result``)."""
        while True:
            event = self.next_event(timeout=timeout)
            if event is None:
                raise TimeoutError(
                    f"request {self.id} produced no token in {timeout}s"
                )
            kind, value = event
            if kind == "token":
                yield value
            else:
                return

    def result(self, timeout: Optional[float] = None) -> List[int]:
        """Block until terminal, then return all emitted token ids
        (including the EOS token when one was sampled)."""
        if not self._done.wait(timeout=timeout):
            raise TimeoutError(f"request {self.id} still {self.status}")
        return list(self.tokens)

    def ledger_snapshot(self, now: Optional[float] = None) -> Dict[str, Any]:
        """The request's cost ledger as the terminal event reports it:
        cumulative counters plus the queue/prefill/decode millisecond
        split from the lifecycle timestamps (hop-local wall added to the
        base a migrated stream carried in). ``now`` lets a LIVE snapshot
        (the migration export) account wall time up to this instant —
        without it a mid-decode hop would ship decode_ms=0 and the
        cumulative split would silently lose the source hop's time."""
        sub = self.submitted_at
        adm = self.admitted_at
        pre = self.prefill_done_at
        fin = self.finished_at
        if fin is not None:
            end = fin
        elif now is not None:
            end = now
        else:
            end = pre or adm or sub
        queue_ms = ((adm if adm is not None else end) - sub) * 1e3
        prefill_ms = (
            ((pre if pre is not None else end) - adm) * 1e3
            if adm is not None else 0.0
        )
        decode_ms = (end - pre) * 1e3 if pre is not None else 0.0
        base = self._ledger_ms_base
        return {
            **{k: int(v) for k, v in self.ledger.items()},
            "queue_ms": round(base["queue_ms"] + max(0.0, queue_ms), 3),
            "prefill_ms": round(base["prefill_ms"] + max(0.0, prefill_ms), 3),
            "decode_ms": round(base["decode_ms"] + max(0.0, decode_ms), 3),
        }

    # -- scheduler side ----------------------------------------------------

    def _emit(self, token: int, now: float) -> None:
        if self.first_token_at is None:
            self.first_token_at = now
        self.tokens.append(token)
        if (
            self.consumer_attached
            and self._events.qsize() >= self.emit_buffer_max
        ):
            # stalled streaming reader: stop buffering (the scheduler
            # notices ``overflowed`` this tick and finishes retryably)
            self.overflowed = True
            return
        self._events.put(("token", token))

    def _finish(
        self,
        status: str,
        now: float,
        error: Optional[str] = None,
        retryable: bool = False,
        retry_after: Optional[float] = None,
    ) -> None:
        self.status = status
        self.error = error
        self.retryable = retryable
        self.retry_after = retry_after
        self.finished_at = now
        tr = self._tracer
        if tr is not None and tr.enabled:
            self._emit_spans(now)
        self._events.put(("done", status))
        self._done.set()

    def _emit_spans(self, fin: float) -> None:
        """The request's span tree, from the lifecycle timestamps already on
        this handle: root ``request`` = [submitted, finished]; phases
        ``queue``/``prefill``/``decode`` partition it wherever the request
        got before its terminal state. Contiguous by construction, so the
        tree is always complete and well-nested — for done, shed, expired,
        cancelled, and faulted outcomes alike."""
        tr = self._tracer
        sub, adm, pre = self.submitted_at, self.admitted_at, self.prefill_done_at
        attrs = {"id": self.rid, "outcome": self.status,
                 "tokens": len(self.tokens)}
        if self.trace_hop is not None:
            # propagated trace context: the stitched fleet trace asserts
            # hop ordering on this attr after clock-offset correction
            attrs["hop"] = self.trace_hop
        if self.error:
            attrs["error"] = self.error
        tr.add("request", self.rid, sub, fin, attrs)
        tr.add("queue", self.rid, sub, adm if adm is not None else fin, None)
        if adm is not None:
            tr.add("prefill", self.rid, adm, pre if pre is not None else fin, None)
        if pre is not None:
            tr.add("decode", self.rid, pre, fin, None)


@dataclasses.dataclass
class _ActiveSlot:
    handle: RequestHandle
    emitted: int = 0
    last_emit_at: Optional[float] = None


@dataclasses.dataclass
class _PrefillJob:
    """A slot mid-chunked-prefill: acquired in the SlotKVCache but not yet
    decoding. ``fill`` counts prompt tokens whose K/V are in the slot's
    rows (prefix-cache hits included); prefill completes when it reaches
    the prompt length and the slot installs into the decode set."""

    handle: RequestHandle
    fill: int = 0


def _percentiles(values: Sequence[float], qs=(50, 90, 99)) -> Dict[str, float]:
    """Nearest-rank percentiles of a host-side sample list (no numpy dance —
    sample counts are small and this must be dependency-free). ceil, not
    round: banker's rounding would make p50 of 5 samples the 2nd-smallest."""
    if not values:
        return {f"p{q}": 0.0 for q in qs}
    ordered = sorted(values)
    out = {}
    for q in qs:
        rank = max(0, min(len(ordered) - 1, math.ceil(q / 100 * len(ordered)) - 1))
        out[f"p{q}"] = ordered[rank]
    return out


def _sample_tail_impl(sampling, last_logits, gen_mask, rngs):
    """The sampling half of the decode tick: sample every slot from its
    own rng chain. Each row reproduces the single-request loop
    bit-for-bit: the rng split order and the [1, V] sample shapes match
    ``generate()`` with B=1, so a slot's trajectory is independent of its
    neighbors. Jitted STANDALONE only by the fused-tail A/B control
    (``fused_tail=False``); the production path inlines it into the single
    fused program below."""
    split = jax.vmap(jax.random.split)(rngs)  # [S, 2, 2]
    rngs, subs = split[:, 0], split[:, 1]

    def sample_row(key, logits_row, mask_row):
        return sample_token(key, logits_row[None], sampling, mask_row[None])[0]

    token = jax.vmap(sample_row)(subs, last_logits, gen_mask)  # [S]
    newly = jax.nn.one_hot(token, gen_mask.shape[1], dtype=jnp.bool_)
    return token, gen_mask | newly, rngs


def _forward_only_impl(model, params, token, cache):
    """The forward half of the decode tick: one fused model apply + the
    per-slot non-finite guard (the training anomaly predicate inlines
    here) so the healthy path pays one dispatch per tick, not two, and the
    [S] mask rides the same device_get as the tokens."""
    logits, vars_out = model.apply(
        {"params": params, "cache": cache}, token[:, None], mutable=["cache"]
    )
    new_logits = logits[:, -1, :].astype(jnp.float32)
    return new_logits, vars_out["cache"], nonfinite_rows(new_logits)


def _fused_step_impl(model, sampling, params, last_logits, cache, gen_mask, rngs):
    """One decode tick as ONE program: the sampling tail + the fused
    forward, COMPOSED from the exact halves the defused A/B control jits
    separately — the fused/defused bit-identity is structural, not a
    copy-discipline promise."""
    token, gen_mask, rngs = _sample_tail_impl(sampling, last_logits, gen_mask, rngs)
    new_logits, cache, bad = _forward_only_impl(model, params, token, cache)
    return token, new_logits, cache, gen_mask, rngs, bad


def _jit_fused_step():
    return jax.jit(_fused_step_impl, static_argnums=(0, 1), donate_argnums=(3, 4, 5, 6))


# one process-wide compiled step shared by every engine (warmup engines in
# benches pre-pay compiles for the measured engine); a breaker rebuild swaps
# in a PRIVATE _jit_fused_step() so a suspect executable is never reused
_FUSED_SHARED = _jit_fused_step()


def _jit_defused_pair():
    return (
        jax.jit(_sample_tail_impl, static_argnums=(0,), donate_argnums=(2, 3)),
        jax.jit(_forward_only_impl, static_argnums=(0,), donate_argnums=(3,)),
    )


_DEFUSED_SHARED = _jit_defused_pair()


def _slice_rows(leaf, ax, offsets, length):
    """Per-row gather of ``length`` sequence positions at each row's own
    offset: leaf [..., S@ax, L@ax+1, ...] -> [S, ..., length, ...] (slot
    axis moved to the front so vmap can pair rows with offsets)."""
    v = jnp.moveaxis(leaf, ax, 0)
    # inside the vmapped row the slot axis is gone, so the sequence axis
    # (originally ax + 1) sits at index ax
    return jax.vmap(
        lambda row, o: jax.lax.dynamic_slice_in_dim(row, o, length, axis=ax)
    )(v, offsets)


def _write_rows(leaf, regions, ax, offsets):
    """Inverse of ``_slice_rows``: scatter per-row regions back at each
    row's offset and restore the original axis order."""
    v = jnp.moveaxis(leaf, ax, 0)
    v = jax.vmap(
        lambda row, r, o: jax.lax.dynamic_update_slice_in_dim(row, r, o, axis=ax)
    )(v, regions, offsets)
    return jnp.moveaxis(v, 0, ax)


def _chunk_prefill_impl(model, axes_items, params, cache, tokens, starts, true_lens, active):
    """One prefill chunk for EVERY mid-prefill slot, written directly into
    the shared slot cache — the fixed-shape [S, C] program at the heart of
    chunked prefill + batched admission.

    Per row: ``tokens`` holds the prompt window at global positions
    ``[starts, starts + C)`` (zero-padded past the prompt; the host clamps
    ``starts`` to ``cache_len - C`` and re-sends earlier tokens in the
    window, whose K/V recompute bit-identically, so the window never
    clamps inside ``dynamic_update_slice``). The model's per-slot decode
    path does the rest: vector cache index = per-row write offset, per-row
    RoPE/ALiBi positions, causal masking against ``q_offset`` so real
    query positions never attend to the window's padded tail.

    Rows NOT mid-prefill (parked or actively decoding) ride along because
    the program's shape is fixed: their clobbered K/V window and index
    cursor are stashed first and restored bit-exactly after the apply, so
    the dispatch is invisible to them. The cache argument is deliberately
    NOT donated: on a fault the engine keeps the pre-chunk cache and fails
    only the prefilling slots (``_on_prefill_fault``) — decode slots
    survive untouched, at the cost of the apply writing fresh buffers.

    Returns ``(cache, last_logits)`` where ``last_logits[s]`` is the f32
    logits row at the prompt's final position — meaningful only for rows
    whose prefill completes in this chunk (``true_lens`` falls inside the
    window); the engine installs exactly those rows.
    """
    axes = dict(axes_items)
    S, C = tokens.shape

    saved_regions: Dict[str, jax.Array] = {}
    saved_index: Dict[str, jax.Array] = {}

    def collect(path, leaf):
        key = jax.tree_util.keystr(path)
        if _leaf_name(path) in INDEX_LEAVES:
            saved_index[key] = leaf
        elif key in axes:
            saved_regions[key] = _slice_rows(leaf, axes[key], starts, C)

    jax.tree_util.tree_map_with_path(collect, cache)

    def set_index(path, leaf):
        if _leaf_name(path) in INDEX_LEAVES:
            return jnp.broadcast_to(starts.astype(leaf.dtype), leaf.shape)
        return leaf

    cache = jax.tree_util.tree_map_with_path(set_index, cache)
    logits, vars_out = model.apply(
        {"params": params, "cache": cache}, tokens, mutable=["cache"]
    )
    new_cache = vars_out["cache"]

    # logits at the prompt's last position, per row (clip keeps the gather
    # in-bounds for rows whose prompt does not end in this window — their
    # value is garbage the engine never reads)
    last = jax.vmap(
        lambda row, i: jax.lax.dynamic_slice_in_dim(row, i, 1, axis=0)[0]
    )(logits, jnp.clip(true_lens - 1 - starts, 0, C - 1)).astype(jnp.float32)

    new_fill = jnp.minimum(starts + C, true_lens)

    def fix(path, leaf):
        key = jax.tree_util.keystr(path)
        if _leaf_name(path) in INDEX_LEAVES:
            # active rows: fill cursor = min(window end, prompt length) —
            # the padded tail of a final chunk stays outside the validity
            # mask exactly like the legacy padded prefill. Inactive rows:
            # their pre-chunk cursor, bit-exact. (broadcast from the right:
            # leaf is [..., S])
            return jnp.where(active, new_fill.astype(leaf.dtype), saved_index[key])
        ax = axes.get(key)
        if ax is None:
            return leaf
        region = _slice_rows(leaf, ax, starts, C)
        keep = active.reshape((S,) + (1,) * (region.ndim - 1))
        return _write_rows(
            leaf, jnp.where(keep, region, saved_regions[key]), ax, starts
        )

    return jax.tree_util.tree_map_with_path(fix, new_cache), last


# shared like _FUSED_SHARED: the statics (model structure, cache axes map)
# compare equal across engines, so warmup engines pre-pay this compile too.
# ONE compiled program per (n_slots, chunk) whatever the prompt-length mix —
# chunked prefill has no per-length bucket family to storm.
_CHUNK_SHARED = jax.jit(_chunk_prefill_impl, static_argnums=(0, 1))


def _paged_chunk_prefill_impl(
    model, params, cache, tokens, starts, true_lens, active, table, index_after
):
    """The paged twin of ``_chunk_prefill_impl`` — one [S, C] chunk for
    every mid-prefill slot, writing through each slot's block table into
    the page pool.

    Paging makes the slab version's stash-and-restore dance unnecessary:
    rows NOT mid-prefill are routed to the TRASH page for the duration of
    the apply (their table rows swap to zeros), so the dispatch cannot
    touch their K/V at all, and index leaves are overwritten wholesale
    afterwards from ``index_after`` — the host knows every row's true
    cursor (fill for prefilling rows, prompt + emitted for decoding rows,
    0 for parked). ``table`` is the authoritative host mirror; the apply
    never mutates it. The cache is deliberately NOT donated (same fault
    isolation as the slab chunk: a fault keeps the pre-chunk pool and
    fails only the prefilling slots)."""
    S, C = tokens.shape

    def pre(path, leaf):
        name = _leaf_name(path)
        if name == TABLE_LEAF:
            routed = jnp.where(active[:, None], table, 0)
            return jnp.broadcast_to(routed, leaf.shape).astype(leaf.dtype)
        if name in INDEX_LEAVES:
            return jnp.broadcast_to(starts, leaf.shape).astype(leaf.dtype)
        return leaf

    staged = jax.tree_util.tree_map_with_path(pre, cache)
    logits, vars_out = model.apply(
        {"params": params, "cache": staged}, tokens, mutable=["cache"]
    )
    new_cache = vars_out["cache"]

    last = jax.vmap(
        lambda row, i: jax.lax.dynamic_slice_in_dim(row, i, 1, axis=0)[0]
    )(logits, jnp.clip(true_lens - 1 - starts, 0, C - 1)).astype(jnp.float32)

    def post(path, leaf):
        name = _leaf_name(path)
        if name == TABLE_LEAF:
            return jnp.broadcast_to(table, leaf.shape).astype(leaf.dtype)
        if name in INDEX_LEAVES:
            return jnp.broadcast_to(index_after, leaf.shape).astype(leaf.dtype)
        return leaf

    return jax.tree_util.tree_map_with_path(post, new_cache), last


_PAGED_CHUNK_SHARED = jax.jit(_paged_chunk_prefill_impl, static_argnums=(0,))


def _spec_step_impl(
    model, sampling, K, params, last_logits, cache, gen_mask, rngs, draft,
    veto, active
):
    """Speculative fused step: sample one token per row (exactly as the
    plain step would), then VERIFY ``K`` host-proposed draft tokens for
    every row in the same single forward — the decode tick emits
    ``1 + n_acc`` tokens per slot instead of 1, at one dispatch.

    Acceptance per the standard draft-and-verify rule (Leviathan et al.
    2211.17192), specialized to the deterministic (point-mass) drafts the
    n-gram proposer emits:

    - greedy: a draft survives iff it equals the model's own processed
      argmax given the verified prefix — the emitted sequence is the plain
      greedy sequence BY CONSTRUCTION (bit-identical; tested);
    - sampling: draft ``d`` at position ``j`` is accepted with probability
      ``p_j(d)`` (its probability under the processed target
      distribution). On rejection nothing further is emitted this tick and
      ``d`` is returned as the row's VETO: the next tick's sample masks it
      out after processing, which is exactly the residual distribution
      ``norm(max(p - q, 0))`` for a point-mass ``q`` — so the emitted
      process remains distributed as plain sampling.

    Carry contract: ``last_logits[s]`` is always the model's distribution
    AFTER consuming everything row ``s`` has emitted — the accepted prefix
    advances it K-for-free, a rejection leaves it at the rejection point.
    The cache index rewinds in-graph to the consumed length (vector index:
    per-row rewind is native); rows not actively decoding (``active``
    False: parked or mid-prefill) restore their pre-tick cursor exactly.
    Requires ``sampling.repetition_penalty == 1.0`` (enforced by the
    engine): the penalty would make in-block positions interdependent.
    """
    S = last_logits.shape[0]
    V = last_logits.shape[1]
    split = jax.vmap(jax.random.split)(rngs)  # [S, 2, 2]
    rngs, subs = split[:, 0], split[:, 1]
    # two independent keys per row: the token sample and the K accept draws
    sub2 = jax.vmap(jax.random.split)(subs)
    k_tok, k_acc = sub2[:, 0], sub2[:, 1]

    arangeV = jnp.arange(V)

    def sample_row(key, logits_row, mask_row, veto_row):
        # mirror of the plain step's sample_row (same [1, V] processed
        # shapes), plus the rejection-rule veto masked AFTER processing;
        # veto = -1 matches nothing. Greedy is veto-neutral by construction
        # (the veto was rejected precisely because it is not the argmax).
        proc = process_logits(logits_row[None], sampling, mask_row[None])
        proc = jnp.where(arangeV[None, :] == veto_row, NEG_INF, proc)
        if sampling.greedy:
            return jnp.argmax(proc, axis=-1).astype(jnp.int32)[0]
        return jax.random.categorical(key, proc, axis=-1).astype(jnp.int32)[0]

    token = jax.vmap(sample_row)(k_tok, last_logits, gen_mask, veto)  # [S]
    x = jnp.concatenate([token[:, None], draft], axis=1)  # [S, K+1]
    logits, vars_out = model.apply(
        {"params": params, "cache": cache}, x, mutable=["cache"]
    )
    cache = vars_out["cache"]
    logits32 = logits.astype(jnp.float32)  # [S, K+1, V]

    flat = logits32.reshape(S * (K + 1), V)
    if sampling.greedy:
        y = jax.vmap(
            lambda row: jnp.argmax(
                process_logits(row[None], sampling, None), axis=-1
            ).astype(jnp.int32)[0]
        )(flat).reshape(S, K + 1)
        ok = (draft == y[:, :K]).astype(jnp.int32)
    else:
        p = jax.vmap(
            lambda row: jax.nn.softmax(
                process_logits(row[None], sampling, None), axis=-1
            )[0]
        )(flat).reshape(S, K + 1, V)
        p_draft = jnp.take_along_axis(
            p[:, :K, :], draft[..., None], axis=-1
        )[..., 0]  # [S, K]
        u = jax.vmap(lambda kk: jax.random.uniform(kk, (K,)))(k_acc)
        ok = (u < p_draft).astype(jnp.int32)
    n_acc = jnp.sum(jnp.cumprod(ok, axis=1), axis=1)  # [S] in [0, K]

    rows = jnp.arange(S)
    # distribution after the last ACCEPTED token — next tick samples from it
    new_logits = logits32[rows, n_acc]
    rejected = draft[rows, jnp.clip(n_acc, 0, K - 1)]
    new_veto = jnp.where(n_acc < K, rejected, -1)
    new_veto = jnp.where(active, new_veto, veto)

    n_emit = 1 + n_acc  # token + accepted drafts
    emitted = jnp.arange(K + 1)[None, :] < n_emit[:, None]  # [S, K+1]
    newly = jnp.any(
        jax.nn.one_hot(x, V, dtype=jnp.bool_) & emitted[..., None], axis=1
    )
    gen_mask = gen_mask | (newly & active[:, None])

    # rewind: the apply advanced every index leaf by K+1; the consumed
    # length is 1 + n_acc for decoding rows, 0 for everyone else (parked
    # and mid-prefill rows restore their pre-tick cursor bit-exactly)
    delta = jnp.where(active, n_emit - (K + 1), -(K + 1)).astype(jnp.int32)

    def rewind(path, leaf):
        if _leaf_name(path) in INDEX_LEAVES:
            return leaf + delta  # [..., S] + [S]: broadcasts from the right
        return leaf

    cache = jax.tree_util.tree_map_with_path(rewind, cache)
    # a non-finite ANYWHERE in the verify block poisons the row: drafts
    # "validated" by garbage logits must not be emitted (the host clamps a
    # bad row to its first token, which was sampled from the PREVIOUS
    # finite distribution — the plain step's exact guarantee)
    bad = nonfinite_rows(logits32)
    return x, n_acc, new_logits, cache, gen_mask, rngs, new_veto, bad


def _jit_spec_step():
    return jax.jit(
        _spec_step_impl, static_argnums=(0, 1, 2), donate_argnums=(4, 5, 6, 7, 9)
    )


# shared across engines like _FUSED_SHARED (statics: model, sampling, K);
# a breaker rebuild swaps in a private instance, same as the plain step
_SPEC_SHARED = _jit_spec_step()


@jax.jit
def _install_rows(last_logits, gen_mask, rngs, mask, logits_rows, keys):
    """Install every completed prefill in ONE dispatch: rows under ``mask``
    get their prefill logits, a cleared penalty mask, and a fresh rng
    chain; other rows pass through untouched. Replaces the per-request
    ``dynamic_update_slice`` install — admission cost no longer scales
    dispatches with the number of requests admitted in a tick."""
    m = mask[:, None]
    return (
        jnp.where(m, logits_rows, last_logits),
        jnp.where(m, jnp.zeros_like(gen_mask), gen_mask),
        jnp.where(m, keys, rngs),
    )


@jax.jit
def _install_import(last_logits, gen_mask, rngs, veto, slot, row, mask_row,
                    key, veto_val):
    """Install ONE imported stream's decode carry (migration receive): the
    exact last_logits/gen_mask/rng/veto the source exported, at the
    destination slot — the continuation is bit-identical to the source
    having kept decoding."""
    zero = jnp.int32(0)
    return (
        jax.lax.dynamic_update_slice(last_logits, row[None], (slot, zero)),
        jax.lax.dynamic_update_slice(gen_mask, mask_row[None], (slot, zero)),
        jax.lax.dynamic_update_slice(rngs, key[None], (slot, zero)),
        jax.lax.dynamic_update_slice(veto, veto_val[None], (slot,)),
    )


class ServingEngine:
    """Slot-scheduled continuous batching over one jitted decode step.

    Sampling semantics (temperature/top-k/top-p/penalty/greedy) are
    ENGINE-level: they are static arguments baked into the compiled fused
    step, so per-request variation would recompile per combination.
    Requests carry what is cheap to vary: prompt, token budget, seed,
    deadline.
    """

    def __init__(
        self,
        cfg,
        params: Any,
        n_slots: int = 4,
        cache_len: Optional[int] = None,
        sampling: SamplingConfig = SamplingConfig(),
        eos_token_id: Optional[int] = None,
        max_queue: int = 64,
        mesh=None,
        metrics=None,
        metrics_interval: int = 0,
        clock: Callable[[], float] = time.monotonic,
        breaker_threshold: int = 3,
        breaker_cooldown: int = 1,
        max_rebuilds: int = 3,
        shed_warmup: int = 8,
        itl_decay: float = 0.9,
        chaos=None,
        prefill_chunk: int = 0,
        prefix_cache_chunks: int = 0,
        max_prefill_buckets: int = 8,
        kv_layout: str = "slab",
        page_size: int = 16,
        page_pool_tokens: int = 0,
        draft_k: int = 0,
        draft_fn: Optional[Callable[[Sequence[int], int], List[int]]] = None,
        fused_tail: bool = True,
        role: str = "mixed",
        page_shipper: Optional[Callable[..., None]] = None,
        obs_dir: Optional[str] = None,
        trace: bool = True,
        trace_capacity: int = 8192,
        flight_capacity: int = 256,
        qos=None,
        emit_buffer_max: int = 1024,
        tenant_buckets_capacity: int = 4096,
    ):
        self.cfg = cfg
        self.cache_len = cache_len or cfg.max_seq_len
        if prefill_chunk < 0:
            raise ValueError("prefill_chunk must be >= 0 (0 = one-shot prefill)")
        if prefix_cache_chunks < 0:
            raise ValueError("prefix_cache_chunks must be >= 0 (0 disables)")
        if prefix_cache_chunks > 0 and prefill_chunk == 0:
            raise ValueError(
                "prefix caching requires chunked prefill (prefill_chunk > 0): "
                "entries are keyed on chunk-aligned token spans"
            )
        if max_prefill_buckets < 1:
            raise ValueError("max_prefill_buckets must be >= 1")
        # a chunk larger than the cache degenerates to one-shot-sized
        # windows; clamp so the window math never exceeds capacity
        self.prefill_chunk = min(prefill_chunk, self.cache_len)
        self.max_prefill_buckets = max_prefill_buckets
        if kv_layout not in ("slab", "paged"):
            raise ValueError(f"kv_layout must be 'slab' or 'paged', got {kv_layout!r}")
        self.kv_layout = kv_layout
        if draft_k < 0:
            raise ValueError("draft_k must be >= 0 (0 disables speculation)")
        if draft_k and sampling.repetition_penalty != 1.0:
            raise ValueError(
                "speculative serving (draft_k > 0) requires "
                "repetition_penalty == 1.0: the penalty makes in-block "
                "positions interdependent (one-shot generate_speculative "
                "emulates it; the batched verify step does not)"
            )
        self.draft_k = int(draft_k)
        self.draft_fn = draft_fn or ngram_propose
        # fused_tail=False is the A/B CONTROL: sampling runs as its own
        # dispatch after the forward (the pre-kernel-lane shape) instead of
        # inside the single decode program. Byte-identical trajectories by
        # construction (same ops, split across two dispatches) — the bench
        # embeds it as the no_fused_tail arm. Production stays fused.
        self.fused_tail = bool(fused_tail)
        if not self.fused_tail and draft_k:
            raise ValueError(
                "fused_tail=False (the A/B control) covers the plain decode "
                "path only; speculative verify (draft_k > 0) is inseparable "
                "from its in-program sampling"
            )
        if role not in ROLES:
            raise ValueError(f"role must be one of {ROLES}, got {role!r}")
        if role != "mixed" and kv_layout != "paged":
            raise ValueError(
                f"role={role!r} requires kv_layout='paged': KV pages are "
                "the unit that ships between disaggregated replicas"
            )
        if role == "prefill" and draft_k:
            raise ValueError(
                "role='prefill' replicas never decode; draft_k must be 0"
            )
        self.role = role
        # the ship seam: callable(payload, target_url, on_done) — provided
        # by the serving front end (HTTP POST to <target>/ingest off the
        # tick thread) or a test harness (direct import into a peer
        # engine). on_done(None) confirms; on_done(err_str) fails the
        # migration retryably (the source stream falls back to recompute).
        self.page_shipper = page_shipper
        self.page_size = int(page_size)
        if kv_layout == "paged":
            if self.prefill_chunk == 0:
                raise ValueError(
                    "kv_layout='paged' requires chunked prefill "
                    "(prefill_chunk > 0): the one-shot insert path has no "
                    "block-table addressing"
                )
            if page_size < 1:
                raise ValueError("page_size must be >= 1")
            if self.cache_len % page_size:
                raise ValueError(
                    f"page_size ({page_size}) must divide cache_len "
                    f"({self.cache_len})"
                )
            if self.prefill_chunk % page_size:
                raise ValueError(
                    f"page_size ({page_size}) must divide prefill_chunk "
                    f"({self.prefill_chunk}): chunk-aligned prefix sharing "
                    "must be page-aligned so divergence starts on a page "
                    "boundary (no live page is ever written by two rows)"
                )
            if page_pool_tokens == 0:
                # slab-equivalent budget: the paged pool defaults to exactly
                # the HBM the slab would have reserved
                page_pool_tokens = n_slots * self.cache_len
            if page_pool_tokens % page_size:
                raise ValueError(
                    f"page_pool_tokens ({page_pool_tokens}) must be a "
                    f"multiple of page_size ({page_size})"
                )
            self.page_pool_tokens = int(page_pool_tokens)
            n_pages = page_pool_tokens // page_size + 1  # + trash page
            self.model = decode_model(
                cfg, self.cache_len, kv_pages=(n_pages, page_size)
            )
        else:
            self.page_pool_tokens = 0
            self.model = decode_model(cfg, self.cache_len)
        self.params = params
        self.sampling = sampling
        self.eos_token_id = eos_token_id
        self.mesh = mesh
        self.now = clock
        self.metrics = metrics
        self.metrics_interval = metrics_interval

        self.n_slots = n_slots
        self.slots = self._make_slots()
        V = cfg.vocab_size
        self._last_logits = jnp.zeros((n_slots, V), jnp.float32)
        self._gen_mask = jnp.zeros((n_slots, V), jnp.bool_)
        self._rngs = jnp.stack([jax.random.PRNGKey(0)] * n_slots)
        # rejection-rule carry: the draft token the verify step rejected
        # last tick, masked out of this tick's sample (-1 = none)
        self._veto = jnp.full((n_slots,), -1, jnp.int32)
        self._active: List[Optional[_ActiveSlot]] = [None] * n_slots
        # slot -> _PrefillJob for slots mid-chunked-prefill (acquired in the
        # SlotKVCache, not yet decoding); only the tick thread touches it
        self._prefilling: Dict[int, _PrefillJob] = {}
        self._prefix_cache_chunks = prefix_cache_chunks
        self._prefix_cache: Optional[PrefixCache] = self._make_prefix_cache()
        self._chunk_fused = _CHUNK_SHARED
        self._paged_chunk = _PAGED_CHUNK_SHARED
        self._spec = _SPEC_SHARED
        self._sample_tail, self._forward_only = _DEFUSED_SHARED
        # compile-family sanitizer (analysis/runtime.py): each labeled jit
        # dispatch site declares the number of distinct cache signatures it
        # may legitimately produce over this engine's lifetime. The fixed-
        # shape discipline says ONE each — the fused decode step, the
        # [S, C] chunk prefill, and the K-draft verify are all single
        # programs whatever the occupancy/prompt mix. A second signature
        # means some per-request axis leaked into a shape or static
        # (strict mode raises listing the signatures; production warns).
        self._ds_decode = bounded_dispatch("engine.decode_step", 1)
        self._ds_prefill = bounded_dispatch("engine.prefill_chunk", 1)
        self._ds_spec = bounded_dispatch("engine.spec_verify", 1)
        # kernel-lane sites (PR 11): the defused control's standalone sample
        # dispatch, and the paged-attention kernel's per-tick signature
        # (table/pool/offset shapes — the kernel itself runs INSIDE the
        # decode/spec program, so this site pins the host-visible inputs
        # that select its compiled family)
        self._ds_sample = bounded_dispatch("engine.sample_tail", 1)
        self._ds_paged = bounded_dispatch("engine.paged_attention", 1)
        # is the paged-attention kernel compiled into the decode program?
        # Same gate the model consults (ops.pallas.paged_attention), so the
        # exported gauge can never disagree with what actually traced.
        from zero_transformer_tpu.ops.pallas import paged_attention as _pa

        self._paged_kernel = kv_layout == "paged" and _pa.supported(
            cfg.attention_impl,
            T=1 + self.draft_k if self.draft_k else 1,
            D=cfg.head_width,
            page_size=self.page_size,
            dtype=resolve_dtype(cfg.compute_dtype),
        )
        # distinct one-shot prefill bucket lengths this engine has compiled
        # (legacy path); bounded by max_prefill_buckets + the capacity bucket
        self._buckets_seen: set = set()
        # did THIS tick do prefill work (chunk, span copy, or one-shot
        # admission)? classifies the tick's ITL samples for attribution
        self._prefill_work = False

        # disaggregation / migration state (tick thread owns placement;
        # other threads only enqueue under the lock)
        self._pending_imports: deque = deque()  # (handle, payload)
        self._migrate_requests: Dict[str, str] = {}  # rid (or "*") -> target
        self._migrating: Dict[int, RequestHandle] = {}  # awaiting ship ack
        self._migrations_in_flight = 0

        # overload isolation (PR 18): the declared class policy (inert
        # defaults when no config — no floors, unlimited buckets), the
        # per-(tenant, class) admission buckets, and the admission queue
        # as per-class deficit-weighted round-robin priced in work tokens
        self.qos = (
            qos if isinstance(qos, QosPolicy) else QosPolicy.from_config(qos)
        )
        self._tenant_buckets = TenantBuckets(
            self.qos, capacity=tenant_buckets_capacity
        )
        self.emit_buffer_max = max(1, int(emit_buffer_max))
        self._queue: ClassQueue = self._make_queue()
        # brownout rung in force on THIS replica (the router's fleet
        # controller pushes transitions via POST /admin/brownout; an
        # engine-local set_brownout serves single-replica deployments)
        self._brownout_rung = BROWNOUT_RUNGS[0]
        self.max_queue = max_queue
        self._lock = threading.Lock()
        self._ids = itertools.count()
        self._tick = 0
        self._dead: Optional[str] = None  # set by _abort; submit() fails fast

        # resilience state (serving/resilience.py primitives)
        self.lifecycle = Lifecycle(clock)
        self._breaker = CircuitBreaker(breaker_threshold, breaker_cooldown)
        self.max_rebuilds = max_rebuilds
        # consecutive-incident rebuild budget: resets when the breaker
        # closes, so a long-lived replica isn't killed by its lifetime
        # trip COUNT after recovering cleanly from each incident
        self._rebuilds_since_recovery = 0
        self._itl_ewma = ItlEwma(decay=itl_decay, warmup=shed_warmup)
        self._chaos = chaos
        self._fused = _FUSED_SHARED  # swapped for a private jit on rebuild
        # staged by reload_params as (tree, swap-event); swapped at tick
        self._pending_params = None
        self._last_reload_event: Optional[threading.Event] = None
        self._drain_deadline: Optional[float] = None
        self._drain_started: Optional[float] = None
        self.drain_latency_s: Optional[float] = None
        # one zeroed single-row cache for the LEGACY one-shot path, built
        # lazily on first use: prefill's apply is functional (never mutates
        # its input), so every admission reuses this template instead of
        # paying an eval_shape retrace + a fresh device allocation per
        # request; the chunked path writes straight into the slot cache and
        # never needs it
        self._prefill_cache = None

        # serving counters / latency samples (host side)
        self.stats: Dict[str, Any] = {
            "submitted": 0,
            "completed": 0,
            "rejected_queue_full": 0,
            "rejected_invalid": 0,
            "expired_queued": 0,
            "expired_decoding": 0,
            "cancelled": 0,
            "tokens_out": 0,
            "peak_occupancy": 0,
            "peak_queue_depth": 0,
            # resilience counters (exported via /metrics and logged as
            # MetricsLogger events so serving incidents land in the same
            # JSONL timeline the training stack writes)
            "tick_faults": 0,
            "poisoned_slots": 0,
            "breaker_trips": 0,
            "shed_infeasible": 0,
            "rejected_draining": 0,
            "drain_forced": 0,
            "reloads": 0,
            "reloads_rejected": 0,
            # prefill-path counters (chunked prefill / prefix cache /
            # legacy bucket cap)
            "prefill_chunks": 0,
            "prefill_faults": 0,
            "prefill_bucket_capped": 0,
            "expired_prefilling": 0,
            # paged-KV counters: allocation pressure (a page fault = the
            # pool was empty and prefix-cache pages had to be reclaimed),
            # and the preemption of last resort when even reclaim failed
            "page_faults": 0,
            "pages_reclaimed": 0,
            "preemptions": 0,
            # speculation counters: acceptance_rate = accepted / drafted
            "spec_ticks": 0,
            "draft_tokens": 0,
            "accepted_tokens": 0,
            # disaggregation / live migration counters: streams shipped out
            # (prefill handoffs + live migrations), streams imported, ship
            # failures (the source stream then fails retryably and the
            # router falls back to re-dispatch-and-recompute), and prefill
            # handoffs specifically (the disagg split of migrations_out)
            "migrations_out": 0,
            "migrations_in": 0,
            "migration_failures": 0,
            "prefill_handoffs": 0,
            # overload-isolation counters (PR 18): per-tenant bucket
            # rejections, queue-full sheds that evicted a LOWER class to
            # keep a higher one, preemptions of running lower-class
            # streams for a waiting higher class, brownout admission
            # rejections + rung transitions, and streams finished because
            # their SSE consumer stalled past the emit-buffer bound
            "rejected_quota": 0,
            "rejected_brownout": 0,
            "shed_lower_class": 0,
            "preempted_for_class": 0,
            "brownout_transitions": 0,
            "stalled_streams": 0,
            # pinned 0 BY CONSTRUCTION: an imported stream installs its
            # shipped pages and never runs prefill for consumed positions
            # (asserted via dest prefill_chunks == 0 in the parity tests).
            # The O(tokens) cost of the recompute fallback is counted on
            # the ROUTER (resume_replayed_tokens) — the replica can't
            # distinguish a resumed-as-prompt request from a long prompt.
            "import_replayed_tokens": 0,
        }
        # observability (obs/): span tracer, Prometheus registry, flight
        # recorder, on-demand profiler. Latency samples land in FIXED-BUCKET
        # histograms — a /metrics read is an O(buckets) walk that never
        # takes the scheduler lock (the pre-PR7 deques made every snapshot
        # sort the 10k-sample history under it)
        self.obs_dir = str(obs_dir) if obs_dir else None
        self.tracer = Tracer(enabled=trace, capacity=trace_capacity, clock=clock)
        self.registry = Registry()
        self.flight = FlightRecorder(
            directory=self.obs_dir, capacity=flight_capacity,
            tracer=self.tracer, clock=clock,
        )
        self._profiler = ProfileWindow(self.obs_dir, prefix="serve")
        self._h_ttft = self.registry.histogram(
            "serve_ttft_seconds",
            "Submit-to-first-token latency (queue wait included)",
            LATENCY_BUCKETS,
        )
        self._h_itl = self.registry.histogram(
            "serve_itl_seconds", "Inter-token latency, all decode ticks",
            LATENCY_BUCKETS,
        )
        # ITL samples from ticks that did NO prefill work — the pure-decode
        # floor; the gap between itl and itl_decode percentiles IS the
        # prefill interference the chunk budget exists to bound
        self._h_itl_decode = self.registry.histogram(
            "serve_itl_decode_seconds",
            "Inter-token latency on ticks with no prefill work (decode floor)",
            LATENCY_BUCKETS,
        )
        self._h_queue_wait = self.registry.histogram(
            "serve_queue_wait_seconds", "Submit-to-slot-admission wait",
            LATENCY_BUCKETS,
        )
        self._h_prefill = self.registry.histogram(
            "serve_prefill_seconds",
            "Admission-to-install prefill latency (prefix hits included)",
            LATENCY_BUCKETS,
        )
        # per-class latency families: the fleet aggregator merges these by
        # name, so per-class SLO objectives (qos_class on an Objective)
        # bind to `serve_ttft_seconds_<class>` with zero aggregator work
        self._h_ttft_class = {
            name: self.registry.histogram(
                f"serve_ttft_seconds_{name}",
                f"Submit-to-first-token latency, {name} class",
                LATENCY_BUCKETS,
            )
            for name in self.qos.names()
        }
        self._h_itl_class = {
            name: self.registry.histogram(
                f"serve_itl_seconds_{name}",
                f"Inter-token latency, {name} class",
                LATENCY_BUCKETS,
            )
            for name in self.qos.names()
        }
        # legacy attribute names: tests and older callers measured the
        # latency deques by len(); Histogram.__len__ keeps that contract
        self._ttft = self._h_ttft
        self._itl = self._h_itl
        self._itl_decode = self._h_itl_decode
        self._register_exports()
        self._started = self.now()

    # ----------------------------------------------------- device-state build

    def _make_slots(self):
        """The KV manager for the configured layout (also the rebuild path:
        a fresh instance means a fresh pool + allocator, nothing reused)."""
        if self.kv_layout == "paged":
            return PagedKVCache(self.model, self.n_slots, mesh=self.mesh)
        return SlotKVCache(self.model, self.n_slots, mesh=self.mesh)

    def _make_queue(self) -> ClassQueue:
        """The admission queue: per-class DWRR priced in work tokens (the
        same unit reservations use), classed by each request's qos."""
        return ClassQueue(
            self.qos,
            cost=lambda h: self._total_need_tokens(h.request),
            class_of=lambda h: h.request.qos,
        )

    # -------------------------------------------------------- qos / brownout

    def _class_slots_in_use(self) -> Dict[str, int]:
        """Decode + mid-prefill slots currently held, per class."""
        counts = {name: 0 for name in self.qos.names()}
        for act in self._active:
            if act is not None:
                counts[self.qos.normalize(act.handle.request.qos)] += 1
        for job in self._prefilling.values():
            counts[self.qos.normalize(job.handle.request.qos)] += 1
        return counts

    def _class_pages_in_use(self) -> Dict[str, int]:
        """KV pages RESERVED per class (the admission-time worst case —
        derivable from the handles alone, so no stateful page accounting
        can drift)."""
        counts = {name: 0 for name in self.qos.names()}
        for act in self._active:
            if act is not None:
                counts[self.qos.normalize(act.handle.request.qos)] += (
                    self.slots.blocks_for(
                        self._total_need_tokens(act.handle.request)
                    )
                )
        for job in self._prefilling.values():
            counts[self.qos.normalize(job.handle.request.qos)] += (
                self.slots.blocks_for(
                    self._total_need_tokens(job.handle.request)
                )
            )
        return counts

    def _slot_eligible(self, cls: str, in_use: Dict[str, int]) -> bool:
        """May class ``cls`` take a free slot now? Only if doing so leaves
        at least the unmet slot floors of every higher class free."""
        floors = {
            name: float(c.slot_floor) for name, c in self.qos.classes.items()
        }
        held = reserved_above(
            self.qos, cls, floors, {k: float(v) for k, v in in_use.items()}
        )
        return self.slots.free_count > held

    def _pages_reserved_above(self, cls: str) -> int:
        """Paged-pool pages held back from class ``cls`` by higher-class
        floors (page_floor_frac x total pool, minus what those classes
        already hold)."""
        total = self.slots.pool.n_pages - 1
        floors = {
            name: float(int(c.page_floor_frac * total))
            for name, c in self.qos.classes.items()
        }
        if not any(floors.values()):
            return 0
        in_use = {
            k: float(v) for k, v in self._class_pages_in_use().items()
        }
        return int(reserved_above(self.qos, cls, floors, in_use))

    @property
    def brownout_rung(self) -> str:
        return self._brownout_rung

    def set_brownout(self, rung: str) -> Dict[str, Any]:
        """Apply a brownout rung (router push or operator override).
        Idempotent; every transition is a flight-recorder event and a
        counter. Rung effects at admission/dispatch time:
        ``no_spec`` disables speculative decode; ``shrink_batch``
        additionally clamps batch-class token budgets; ``suspend_batch``
        additionally rejects batch admission (retryable, class
        Retry-After)."""
        if rung not in BROWNOUT_RUNGS:
            raise ValueError(
                f"unknown brownout rung {rung!r} (rungs: {BROWNOUT_RUNGS})"
            )
        old = self._brownout_rung
        if rung != old:
            self._brownout_rung = rung
            self.stats["brownout_transitions"] += 1
            self._event("brownout_rung", old=old, new=rung)
        return {"rung": self._brownout_rung, "previous": old}

    @property
    def _spec_enabled(self) -> bool:
        return not rung_at_least(self._brownout_rung, "no_spec")

    def _maybe_preempt_for_class(self) -> None:
        """With zero free slots and a gold request waiting, preempt ONE
        running stream of the lowest active class (strictly lower-ranked
        than the waiter) — retryable finish, so the router re-dispatches
        it; the freed slot admits the gold request this same tick. The
        least-progressed victim loses the least work. Never fires across
        equal ranks, so batch-vs-batch contention stays FIFO."""
        if self.slots.free_count:
            return
        with self._lock:
            waiting = self._queue.best_waiting_rank()
        if waiting is None or waiting != 0:  # only the TOP class preempts
            return
        victim_slot, victim_rank, victim_emitted = None, -1, -1
        for slot, act in enumerate(self._active):
            if act is None:
                continue
            rank = self.qos.rank(act.handle.request.qos)
            if rank <= waiting:
                continue
            # lowest class first; among equals, least progress lost
            if rank > victim_rank or (
                rank == victim_rank and act.emitted < victim_emitted
            ):
                victim_slot, victim_rank, victim_emitted = (
                    slot, rank, act.emitted
                )
        if victim_slot is None:
            return
        now = self.now()
        victim = self._active[victim_slot]
        cls = self.qos.class_of(victim.handle.request.qos)
        victim.handle._finish(
            FAILED, now,
            error=(
                f"preempted for higher QoS class (retryable): "
                f"{cls.name} stream yielded its slot"
            ),
            retryable=True, retry_after=cls.retry_after_s,
        )
        self.stats["preempted_for_class"] += 1
        self._retire([victim_slot])
        self._event(
            "qos_preemption", victim_class=cls.name,
            emitted=victim_emitted,
        )

    def _make_prefix_cache(self) -> Optional[PrefixCache]:
        if not (self.prefill_chunk and self._prefix_cache_chunks):
            return None
        if self.kv_layout == "paged":
            # page-id entries refcounted against THIS pool instance — must
            # be rebuilt whenever the pool is (reload keeps the pool and
            # only flushes)
            return PagedPrefixIndex(
                self.prefill_chunk, self._prefix_cache_chunks, self.slots.pool
            )
        return PrefixCache(self.prefill_chunk, self._prefix_cache_chunks)

    def _total_need_tokens(self, request: Request) -> int:
        """Worst-case cache positions the request can ever write: prompt +
        budget, plus the draft window when speculating (the verify forward
        writes K draft positions past the cursor before the rewind)."""
        return min(
            len(request.prompt) + request.max_new_tokens + self.draft_k,
            self.cache_len,
        )

    # ------------------------------------------------------------- admission

    def _validate(self, request: Request) -> Optional[str]:
        T = len(request.prompt)
        if T < 1:
            return "empty prompt"
        if request.max_new_tokens < 1:
            return "max_new_tokens must be >= 1"
        # same bound as generate()._start_decode: the final token is never
        # fed back, so the cache holds T + max_new - 1 positions
        if T + request.max_new_tokens - 1 > self.cache_len:
            return (
                f"prompt ({T}) + max_new_tokens ({request.max_new_tokens}) "
                f"exceeds cache_len ({self.cache_len})"
            )
        if self.kv_layout == "paged" and self.slots.blocks_for(
            self._total_need_tokens(request)
        ) > self.slots.n_pages - 1:
            # bigger than the ENTIRE pool: admission's capacity check could
            # never pass, and a FIFO queue would stall behind it forever —
            # reject at submit instead
            return (
                f"prompt ({T}) + max_new_tokens ({request.max_new_tokens}) "
                f"needs more KV pages than the whole pool holds "
                f"({self.page_pool_tokens} token positions); raise "
                f"--page-pool-tokens or lower the request"
            )
        if (
            self.draft_k
            and T + request.max_new_tokens + self.draft_k > self.cache_len
        ):
            # the verify forward writes K positions past the final cursor
            # before rewinding (mirrors generate_speculative's bound); a
            # clamped write would silently corrupt the row's tail instead
            return (
                f"prompt ({T}) + max_new_tokens ({request.max_new_tokens}) "
                f"+ draft_k ({self.draft_k}) exceeds cache_len "
                f"({self.cache_len}); lower one of them"
            )
        if (
            self.cfg.position == "learned"
            and T + request.max_new_tokens > self.cfg.max_seq_len
        ):
            return "learned positions cannot extrapolate past max_seq_len"
        if request.prefill_to is not None and self.kv_layout != "paged":
            return "prefill_to requires kv_layout='paged' (pages ship)"
        if self.role == "prefill" and request.prefill_to is None:
            return (
                "this is a prefill-role replica: requests must name a "
                "decode target (prefill_to)"
            )
        return None

    def submit(
        self,
        prompt: Sequence[int],
        max_new_tokens: int = 32,
        seed: int = 0,
        deadline: Optional[float] = None,
        timeout: Optional[float] = None,
        request_id: Optional[str] = None,
        prefill_to: Optional[str] = None,
        trace_hop: Optional[int] = None,
        tenant: str = "anon",
        qos: Optional[str] = None,
    ) -> RequestHandle:
        """Enqueue a request; returns its handle immediately.

        ``timeout`` (seconds from now) is sugar for an absolute ``deadline``.
        A full queue or invalid request returns a handle already finished as
        ``rejected`` (callers map that to HTTP 429 / 400) — the error string
        says which. ``request_id`` threads an inbound correlation id
        (``X-Request-Id``) through the span tree and response; omitted, one
        is generated here at admission. ``trace_hop`` is the router's hop
        index for this dispatch (``X-Trace-Hop``) — recorded on the span
        tree so the stitched fleet trace can order hops across processes.
        ``tenant``/``qos`` (``X-Tenant-Key`` / ``X-QoS-Class``) select the
        token bucket the request is charged to and the class it queues,
        sheds, and browns out as.
        """
        now = self.now()
        if timeout is not None:
            deadline = now + timeout if deadline is None else min(deadline, now + timeout)
        qos_name = self.qos.normalize(qos)
        cls = self.qos.classes[qos_name]
        max_new_tokens = int(max_new_tokens)
        if (
            rung_at_least(self._brownout_rung, "shrink_batch")
            and cls.brownout_max_new_tokens is not None
            and max_new_tokens > cls.brownout_max_new_tokens
        ):
            # brownout rung 2: the class keeps serving, on a shrunken
            # budget — graceful degradation before any admission is cut
            max_new_tokens = cls.brownout_max_new_tokens
        request = Request(
            list(prompt), max_new_tokens, int(seed), deadline,
            prefill_to=prefill_to,
            tenant=str(tenant or "anon")[:64], qos=qos_name,
        )
        handle = RequestHandle(request, next(self._ids), now, request_id=request_id)
        handle._tracer = self.tracer
        handle.trace_hop = trace_hop
        handle.emit_buffer_max = self.emit_buffer_max
        invalid = self._validate(request)
        with self._lock:
            if self._dead is not None:
                # the scheduler is gone — nothing will ever drain the queue,
                # so enqueueing would hang the caller forever (checked under
                # the lock: _abort drains the queue under the same lock)
                handle._finish(FAILED, now, error=self._dead)
                return handle
            if self.lifecycle.state == DRAINING:
                # admission is closed; in-flight generations finish, new
                # traffic belongs on another replica (server: 503 +
                # Retry-After, sized to the remaining drain window)
                self.stats["rejected_draining"] += 1
                left = (
                    max(1.0, self._drain_deadline - now)
                    if self._drain_deadline is not None
                    else 1.0
                )
                handle._finish(
                    REJECTED, now, error="server draining; retry elsewhere",
                    retryable=True, retry_after=left,
                )
                return handle
            self.stats["submitted"] += 1
            if invalid is not None:
                self.stats["rejected_invalid"] += 1
                handle._finish(REJECTED, now, error=invalid)
                return handle
            if (
                rung_at_least(self._brownout_rung, "suspend_batch")
                and self.qos.rank(qos_name) == len(self.qos.names()) - 1
            ):
                # brownout rung 3: the lowest class stops admitting
                # entirely — its budget already shrank at rung 2; now its
                # traffic waits out the overload elsewhere
                self.stats["rejected_brownout"] += 1
                handle._finish(
                    REJECTED, now,
                    error=(
                        f"brownout ({self._brownout_rung}): "
                        f"{qos_name} admission suspended; retry later"
                    ),
                    retryable=True, retry_after=cls.retry_after_s,
                )
                return handle
            quota_wait = self._tenant_buckets.take(
                request.tenant, qos_name,
                len(request.prompt) + request.max_new_tokens, now,
            )
            if quota_wait > 0:
                # the tenant's own bucket is dry — its flood is ITS
                # problem; every other tenant's admission is untouched
                self.stats["rejected_quota"] += 1
                handle._finish(
                    REJECTED, now,
                    error=(
                        f"tenant quota exhausted ({qos_name}); "
                        f"retry later"
                    ),
                    retryable=True, retry_after=quota_wait,
                )
                return handle
            if len(self._queue) >= self.max_queue:
                # queue-full pressure evicts the newest STRICTLY-lower
                # class request (retryably) before rejecting a higher one
                victim = self._queue.pop_lowest_class(
                    above_rank=self.qos.rank(qos_name)
                )
                if victim is not None:
                    vcls = self.qos.class_of(victim.request.qos)
                    self.stats["shed_lower_class"] += 1
                    victim._finish(
                        REJECTED, now,
                        error=(
                            f"queue full; shed for higher QoS class "
                            f"({vcls.name} yielded); retry later"
                        ),
                        retryable=True, retry_after=vcls.retry_after_s,
                    )
                else:
                    self.stats["rejected_queue_full"] += 1
                    handle._finish(
                        REJECTED, now,
                        error=f"queue full ({self.max_queue} waiting); retry later",
                        retryable=True, retry_after=max(1.0, cls.retry_after_s),
                    )
                    return handle
            if request.deadline is not None and infeasible_deadline(
                request.deadline, now, request.max_new_tokens,
                len(self._queue), self.n_slots, self._itl_ewma,
            ):
                # provably cannot finish in time: a fast honest 503 now
                # beats decoding tokens nobody will wait for (overload
                # degrades into sheds, not timeout storms)
                self.stats["shed_infeasible"] += 1
                handle._finish(
                    REJECTED, now,
                    error="deadline infeasible at current load (shed)",
                    retryable=True, retry_after=1.0,
                )
                return handle
            self._queue.append(handle)
            self.stats["peak_queue_depth"] = max(
                self.stats["peak_queue_depth"], len(self._queue)
            )
        return handle

    @property
    def queue_depth(self) -> int:
        with self._lock:
            return len(self._queue)

    @property
    def active_count(self) -> int:
        return sum(1 for a in self._active if a is not None)

    @property
    def free_pages(self) -> int:
        """Spare KV capacity, in the unit the layout allocates: free pool
        pages when paged, free decode slots when slab. A fleet router reads
        this from /healthz as an admission input — "how much more can this
        replica take" — without caring which layout backs it."""
        if self.kv_layout == "paged":
            return self.slots.pool.free_count
        return max(0, self.n_slots - self.active_count - len(self._prefilling))

    # --------------------------------------------------------------- prefill

    def _bucket(self, length: int) -> int:
        """Smallest power-of-two >= length (floor 8) that the cache admits —
        one compiled prefill per bucket instead of one per prompt length.

        The distinct-bucket count is CAPPED (``max_prefill_buckets``): each
        compiled bucket is a whole XLA program held for the replica's
        lifetime, so unbounded prompt-length diversity would otherwise
        compile-storm a long-lived server. Past the cap, new lengths round
        UP to the smallest already-compiled bucket that fits (worst case
        the capacity bucket — always admissible) and the event is counted
        (``prefill_bucket_capped``) so the storm is visible in /metrics
        instead of silent."""
        cap = self.cache_len
        if self.cfg.position == "learned":
            cap = min(cap, self.cfg.max_seq_len)
        b = 8
        while b < length:
            b *= 2
        b = min(b, cap)
        if b not in self._buckets_seen:
            if len(self._buckets_seen) >= self.max_prefill_buckets:
                self.stats["prefill_bucket_capped"] += 1
                fitting = [x for x in self._buckets_seen if x >= length]
                b = min(fitting) if fitting else cap
            self._buckets_seen.add(b)  # cap bucket may exceed the budget by 1
        return b

    @functools.partial(jax.jit, static_argnums=(0,))
    def _prefill_padded(model, params, padded, cache, true_len):  # noqa: N805
        """Right-padded prefill. Causality makes K/V at positions < true_len
        and the logits at true_len-1 exact regardless of the padding. The
        returned cache's index leaves are whatever the padded apply left
        (the bucket length) — ``SlotKVCache.insert`` alone owns setting the
        slot's index to ``true_len``, so decode OVERWRITES the padded
        garbage K/V progressively and the validity mask hides the rest."""
        logits, vars_out = model.apply(
            {"params": params, "cache": cache}, padded, mutable=["cache"]
        )
        last = jax.lax.dynamic_slice_in_dim(logits, true_len - 1, 1, axis=1)
        return last[:, 0].astype(jnp.float32), vars_out["cache"]

    def _prefill(self, prompt: Sequence[int]):
        if self._prefill_cache is None:
            self._prefill_cache = init_cache(self.model, 1, mesh=self.mesh)
        T = len(prompt)
        bucket = self._bucket(T)
        padded = jnp.asarray(
            [list(prompt) + [0] * (bucket - T)], jnp.int32
        )
        return _in_mesh(
            self.mesh,
            ServingEngine._prefill_padded,
            self.model,
            self.params,
            padded,
            self._prefill_cache,
            jnp.int32(T),
        )

    # -------------------------------------------------------------- schedule

    def _pop_queue(
        self, eligible=None,
    ) -> Optional[RequestHandle]:
        """Pop the next admissible queued handle (DWRR-fair across QoS
        classes; ``eligible`` gates classes whose admission would eat a
        higher class's reservation floor), finishing cancelled / expired
        ones on the way; None when nothing is admissible."""
        with self._lock:
            now = self.now()
            while self._queue:
                cand = self._queue.popleft(eligible=eligible)
                if cand is None:
                    return None
                if cand._cancel.is_set():
                    self.stats["cancelled"] += 1
                    cand._finish(CANCELLED, now)
                elif cand.request.deadline is not None and now > cand.request.deadline:
                    self.stats["expired_queued"] += 1
                    cand._finish(EXPIRED, now, error="deadline expired in queue")
                else:
                    return cand
        return None

    def _admit(self) -> None:
        if self.prefill_chunk:
            self._admit_chunked()
        else:
            self._admit_oneshot()

    def _admit_chunked(self) -> None:
        """Claim a slot per admissible queued request and start its chunked
        prefill. Prefix-cache hits land here: the slab path copies the
        cached chunk-aligned K/V spans into the slot's rows; the PAGED path
        just maps the cached pages into the slot's block table (refcount
        bumps — zero K/V bytes move). Either way the chunk loop starts at
        the first NOVEL chunk, and the chunk forwards themselves happen in
        ``_prefill_tick``, shared across every mid-prefill slot — admission
        of N requests is one batch, not N prefills.

        Paged admission is CAPACITY-CHECKED: the request's worst case
        (prompt + budget + draft headroom, minus whatever the hit covers)
        is reserved in the page pool up front, so an admitted stream can
        never hit a mid-decode out-of-pages fault — when the pool can't
        cover it (even after reclaiming cold prefix-cache pages), the
        request WAITS at the queue head instead. That waiting is the
        capacity signal the loadgen sweep measures."""
        paged = self.kv_layout == "paged"
        self._maybe_preempt_for_class()
        while self.slots.free_count:
            in_use = self._class_slots_in_use()
            handle = self._pop_queue(
                eligible=lambda c: self._slot_eligible(c, in_use)
            )
            if handle is None:
                return
            if paged and not self._paged_admission_fits(handle):
                # back at the HEAD: admission stays FIFO, and the next
                # retirement frees the pages this request is waiting for
                with self._lock:
                    self._queue.appendleft(handle)
                return
            slot = self.slots.acquire()
            fill = 0
            try:
                if self._prefix_cache is not None:
                    fill, hits = self._prefix_cache.lookup(handle.request.prompt)
                    if hits and paged:
                        self.slots.share(
                            slot, [p for entry in hits for p in entry]
                        )
                    elif hits:
                        # all hit chunks land in one dispatch — a deep hit
                        # must not cost one dispatch per chunk it skipped
                        self.slots.write_spans(hits, slot)
                        self._prefill_work = True
                if paged:
                    self.slots.reserve(
                        slot, self._total_need_tokens(handle.request)
                    )
            except Exception as exc:
                # the popped handle is in neither the queue nor any slot
                # table yet, so _abort() cannot reach it — finish it HERE
                handle._finish(
                    FAILED, self.now(), error=f"admission failed: {exc!r}"
                )
                raise
            handle.prefix_hit_tokens = fill
            handle.admitted_at = self.now()
            self._h_queue_wait.observe(handle.admitted_at - handle.submitted_at)
            handle.status = RUNNING
            self._prefilling[slot] = _PrefillJob(handle, fill=fill)

    def _paged_admission_fits(self, handle: RequestHandle) -> bool:
        """True when the page pool can cover the request's reservation
        (after the prefix hit it is about to take). A shortfall first
        reclaims cold prefix-cache pages (a PAGE FAULT — counted), then
        gives up and lets the request wait."""
        need_total = self.slots.blocks_for(self._total_need_tokens(handle.request))
        # page reservation floors: pages held back for higher classes are
        # invisible to THIS class's admission (batch can never consume the
        # pool headroom gold admission needs)
        held_above = self._pages_reserved_above(handle.request.qos)
        for attempt in (0, 1):
            hit_blocks = 0
            if self._prefix_cache is not None:
                fill, _ = self._prefix_cache.walk(handle.request.prompt)
                hit_blocks = fill // self.page_size
            shortfall = (need_total - hit_blocks) - (
                self.slots.pool.available - held_above
            )
            if shortfall <= 0:
                return True
            if attempt or self._prefix_cache is None or not len(self._prefix_cache):
                return False
            # reclaim may evict the very entries the hit would have used —
            # the re-walk above recomputes the hit honestly on retry
            self.stats["page_faults"] += 1
            freed = self._prefix_cache.reclaim(shortfall)
            self.stats["pages_reclaimed"] += freed
        return False

    def _admit_oneshot(self) -> None:
        """Legacy one-shot path (``prefill_chunk=0``): per-request bucketed
        prefill + cache insert, with the install dispatches for EVERYTHING
        admitted this pass coalesced into one ``_install_rows`` call."""
        installs: List[tuple] = []
        try:
            self._maybe_preempt_for_class()
            while self.slots.free_count:
                in_use = self._class_slots_in_use()
                handle = self._pop_queue(
                    eligible=lambda c: self._slot_eligible(c, in_use)
                )
                if handle is None:
                    return
                handle.admitted_at = self.now()
                self._h_queue_wait.observe(
                    handle.admitted_at - handle.submitted_at
                )
                try:
                    logits_row, small_cache = self._prefill(handle.request.prompt)
                    slot = self.slots.acquire()
                    self.slots.insert(
                        small_cache, slot, len(handle.request.prompt)
                    )
                except Exception as exc:
                    # the popped handle is in neither the queue nor _active,
                    # so _abort() cannot reach it — finish it HERE or its
                    # client hangs forever while everyone else gets a clean
                    # failure
                    handle._finish(
                        FAILED, self.now(), error=f"admission failed: {exc!r}"
                    )
                    raise
                handle.status = RUNNING
                handle.prefill_done_at = self.now()
                self._h_prefill.observe(
                    handle.prefill_done_at - handle.admitted_at
                )
                self._active[slot] = _ActiveSlot(handle)
                installs.append(
                    (slot, logits_row[0], jax.random.PRNGKey(handle.request.seed))
                )
                self.stats["peak_occupancy"] = max(
                    self.stats["peak_occupancy"], self.active_count
                )
        finally:
            # the finally matters: admissions that succeeded BEFORE a failed
            # one must still install, or their slots decode from stale row
            # state next tick
            if installs:
                self._prefill_work = True
                self._flush_installs(installs)

    def _flush_installs(self, installs: List[tuple]) -> None:
        """One ``_install_rows`` dispatch for [(slot, logits_row, key), ...]."""
        mask = [False] * self.n_slots
        zero_row = jnp.zeros((self.cfg.vocab_size,), jnp.float32)
        zero_key = jnp.zeros((2,), jnp.uint32)
        rows = [zero_row] * self.n_slots
        keys = [zero_key] * self.n_slots
        for slot, row, key in installs:
            mask[slot], rows[slot], keys[slot] = True, row, key
        self._last_logits, self._gen_mask, self._rngs = _in_mesh(
            self.mesh,
            _install_rows,
            self._last_logits,
            self._gen_mask,
            self._rngs,
            jnp.asarray(mask, jnp.bool_),
            jnp.stack(rows),
            jnp.stack(keys),
        )
        if self.draft_k:
            self._veto = jnp.where(jnp.asarray(mask, jnp.bool_), -1, self._veto)

    # ------------------------------------------------------- chunked prefill

    # graftlint: hot-path
    # graftlint: supervised-seam
    def _prefill_tick(self) -> bool:
        """Process ONE chunk for every mid-prefill slot in a single
        fixed-shape [n_slots, chunk] dispatch, then install the slots whose
        prompt completed (their decode starts this same tick, exactly as
        the legacy path's would). Supervised: a fault fails ONLY the
        prefilling slots — the chunk program does not donate the cache, so
        decoding slots keep their buffers and the tick proceeds to a
        normal fused decode."""
        if not self._prefilling:
            return False
        self._prefill_work = True
        C, L, S = self.prefill_chunk, self.cache_len, self.n_slots
        paged = self.kv_layout == "paged"
        tokens = [[0] * C for _ in range(S)]
        starts = [0] * S
        lens = [0] * S
        active = [False] * S
        faulted: List[int] = []
        for slot, job in self._prefilling.items():
            prompt = job.handle.request.prompt
            # clamp the window to capacity: the final chunk of a prompt
            # ending near the cap re-sends a few earlier tokens (their K/V
            # recompute bit-identically — the forward is deterministic)
            # instead of letting the device write clamp out of alignment.
            # (Paged: the re-sent overlap may rewrite SHARED pages — with
            # bit-identical values, by the same determinism argument, so no
            # copy-on-write is spent on it.)
            w = min(job.fill, L - C)
            # pages cover only REAL prompt positions: the window's padded
            # tail past len(prompt) routes to the trash page (unallocated
            # blocks map there), and ensuring w + C would draw pages beyond
            # the slot's admission reservation — stealing from already-
            # admitted neighbors and breaking the no-mid-flight-fault
            # invariant
            if paged and not self._ensure_pages_or_reclaim(
                slot, min(w + C, len(prompt))
            ):
                faulted.append(slot)
                continue
            window = prompt[w : w + C]
            tokens[slot][: len(window)] = [int(t) for t in window]
            starts[slot], lens[slot], active[slot] = w, len(prompt), True
        if faulted:
            # reservation-backed allocation makes this unreachable unless
            # bookkeeping rots; fail ONLY the starved jobs, loudly
            now = self.now()
            for slot in faulted:
                job = self._prefilling.pop(slot)
                self.stats["preemptions"] += 1
                job.handle._finish(
                    FAILED, now,
                    error="KV page pool exhausted during prefill (retryable)",
                    retryable=True,
                )
            self.slots.release(faulted)
            self._event("page_preemption", slots=len(faulted), phase="prefill")
            if not self._prefilling:
                return True
        t_chunk = self.now() if self.tracer.enabled else 0.0
        try:
            if self._chaos is not None:
                self._chaos.on_prefill_chunk(self._tick)
            if paged:
                chunk_args = (
                    self.model,
                    self.params,
                    self.slots.cache,
                    jnp.asarray(tokens, jnp.int32),
                    jnp.asarray(starts, jnp.int32),
                    jnp.asarray(lens, jnp.int32),
                    jnp.asarray(active, jnp.bool_),
                    jnp.asarray(self.slots.table),
                    jnp.asarray(self._index_after(starts, lens, active), jnp.int32),
                )
                # observe skips model+params (engine-lifetime constants):
                # the describe walk stays O(per-tick args), not O(params)
                self._ds_prefill.observe(*chunk_args[2:])
                cache, last = _in_mesh(self.mesh, self._paged_chunk, *chunk_args)
            else:
                chunk_args = (
                    self.model,
                    self.slots.axes_items,
                    self.params,
                    self.slots.cache,
                    jnp.asarray(tokens, jnp.int32),
                    jnp.asarray(starts, jnp.int32),
                    jnp.asarray(lens, jnp.int32),
                    jnp.asarray(active, jnp.bool_),
                )
                # skip model (0) + params (2); axes_items are cache statics
                self._ds_prefill.observe(chunk_args[1], *chunk_args[3:])
                cache, last = _in_mesh(self.mesh, self._chunk_fused, *chunk_args)
        except CompileFamilyExceeded:
            # strict-mode sanitizer trip: the whole point is the readable
            # signature listing — it must reach the test harness, not be
            # classified as a prefill fault and fed to the breaker
            raise
        except Exception as exc:
            self._on_prefill_fault(exc)
            return True
        if self.tracer.enabled:
            self.tracer.add(
                "prefill_chunk", "engine", t_chunk, self.now(),
                {"tick": self._tick, "slots": sum(active)},
            )
        self.slots.cache = cache
        self.stats["prefill_chunks"] += sum(active)
        completed = []
        for slot, job in self._prefilling.items():
            if active[slot]:
                # ledger attribution: this request paid for one chunk row
                # of the batched dispatch (sums to stats["prefill_chunks"])
                job.handle.ledger["prefill_chunks"] += 1
            job.fill = min(starts[slot] + C, lens[slot])
            if job.fill >= lens[slot]:
                completed.append((slot, job))
        if completed:
            self._install_completed(completed, last)
        return True

    def _index_after(self, starts, lens, active) -> List[int]:
        """Every row's true post-chunk cursor, host-derived (the paged
        chunk program overwrites index leaves wholesale instead of the slab
        path's stash-and-restore): mid-prefill rows advance their fill,
        decoding rows sit at prompt + emitted, parked rows at zero."""
        out = [0] * self.n_slots
        C = self.prefill_chunk
        for slot in range(self.n_slots):
            if active[slot]:
                out[slot] = min(starts[slot] + C, lens[slot])
            elif self._active[slot] is not None:
                act = self._active[slot]
                out[slot] = len(act.handle.request.prompt) + act.emitted
        return out

    def _ensure_pages_or_reclaim(self, slot: int, tokens: int) -> bool:
        """Grow ``slot``'s block table to cover ``tokens`` positions;
        on pool exhaustion reclaim cold prefix-cache pages (page fault)
        and retry once. Reservations make failure a bookkeeping bug, but
        the path stays defensive rather than trusting the proof."""
        tokens = min(tokens, self.cache_len)
        if self.slots.ensure(slot, tokens):
            return True
        self.stats["page_faults"] += 1
        if self._prefix_cache is not None and len(self._prefix_cache):
            need = self.slots.blocks_for(tokens) - self.slots.alloc_blocks[slot]
            freed = self._prefix_cache.reclaim(need)
            self.stats["pages_reclaimed"] += freed
            if self.slots.ensure(slot, tokens):
                return True
        return False

    # graftlint: hot-path
    def _handoff_completed(self, ship, last_rows) -> None:
        """Disaggregation SEND: a finished prefill whose request names a
        decode target ships its pages + first-token logits there instead of
        installing into this replica's decode set. The destination installs
        the exact carry a local install would have (logits row at
        true_len - 1, PRNGKey(seed), cleared mask/veto), so the handed-off
        stream is byte-identical to having decoded here — with zero
        recomputed tokens."""
        # graftlint: allow[host-sync-in-hot-path] reason=THE designed handoff sync — one device_get of the shipping rows' logits (and seeds' keys), only on prefill-role completions
        rows = jax.device_get(last_rows)
        now = self.now()
        for slot, job in ship:
            handle = job.handle
            handle.prefill_done_at = now
            if handle.admitted_at is not None:
                self._h_prefill.observe(now - handle.admitted_at)
            # bank the prefix BEFORE detaching: the banked pages' refcounts
            # survive the slot release, so the prefill replica's chunk
            # cache actually accumulates — the whole point of the router's
            # prefill affinity on a disaggregated fleet
            self._bank_prefix(slot, handle)
            try:
                span = self.slots.export_page_span(
                    slot, len(handle.request.prompt)
                )
            except Exception as exc:  # a bad export fails ONLY this stream, retryably
                self._detach_slot(slot, True)
                self._migration_failed(handle, f"export failed: {exc!r}")
                continue
            import numpy as _np

            leaves = dict(span["leaves"])
            leaves["carry/last_logits"] = _np.asarray(
                rows[slot], _np.float32
            )
            leaves["carry/gen_mask"] = _np.zeros(
                (self.cfg.vocab_size,), _np.bool_
            )
            # graftlint: allow[host-sync-in-hot-path] reason=tiny PRNGKey materialization for the wire payload, handoff-only
            key_host = jax.device_get(jax.random.PRNGKey(handle.request.seed))
            leaves["carry/rng"] = _np.asarray(key_host, _np.uint32)
            payload = {
                **self._stream_meta(
                    handle, list(handle.request.prompt),
                    handle.request.max_new_tokens,
                ),
                "kind": "decode",
                "veto": -1,
                "page_size": span["page_size"],
                "n_blocks": span["n_blocks"],
                "n_tokens": span["n_tokens"],
                "leaves": leaves,
            }
            self._detach_slot(slot, True)
            with self._lock:
                self._migrating[handle.id] = handle
                self._migrations_in_flight += 1
            self._ship(payload, handle.request.prefill_to, handle)

    def _install_completed(self, completed, last_rows) -> None:
        """Move slots whose prefill just finished into the decode set (one
        coalesced install), then bank their chunk-aligned prefix spans so
        the NEXT prompt sharing the prefix skips them. Completions whose
        request names a decode target (``prefill_to``) ship instead."""
        ship = [
            (s, j) for s, j in completed
            if j.handle.request.prefill_to is not None
        ]
        if ship:
            self._handoff_completed(ship, last_rows)
            completed = [
                (s, j) for s, j in completed
                if j.handle.request.prefill_to is None
            ]
            if not completed:
                return
        mask = [False] * self.n_slots
        zero_key = jnp.zeros((2,), jnp.uint32)
        keys = [zero_key] * self.n_slots
        for slot, job in completed:
            mask[slot] = True
            keys[slot] = jax.random.PRNGKey(job.handle.request.seed)
        self._last_logits, self._gen_mask, self._rngs = _in_mesh(
            self.mesh,
            _install_rows,
            self._last_logits,
            self._gen_mask,
            self._rngs,
            jnp.asarray(mask, jnp.bool_),
            last_rows,
            jnp.stack(keys),
        )
        if self.draft_k:
            # fresh request, fresh rejection-rule carry
            self._veto = jnp.where(
                jnp.asarray(mask, jnp.bool_), -1, self._veto
            )
        t_done = self.now()
        for slot, job in completed:
            del self._prefilling[slot]
            job.handle.prefill_done_at = t_done
            if job.handle.admitted_at is not None:
                self._h_prefill.observe(t_done - job.handle.admitted_at)
            self._active[slot] = _ActiveSlot(job.handle)
            self.stats["peak_occupancy"] = max(
                self.stats["peak_occupancy"], self.active_count
            )
            self._bank_prefix(slot, job.handle)

    def _bank_prefix(self, slot: int, handle: RequestHandle) -> None:
        """Bank a completed prefill's chunk-aligned prefix spans so the
        NEXT prompt sharing the prefix skips them. Store BEFORE the first
        decode write (and before a handoff detaches the slot): positions
        [0, T) are all real prompt K/V right now. Slab: one extraction
        dispatch covers every chunk-aligned span. Paged: banking is PURE
        BOOKKEEPING — the slot's pages get one more reference and their
        ids land in the index; no bytes move (the reference survives the
        slot's release, which is what lets prefill-role replicas keep a
        live chunk cache). Skipped entirely when the cache already holds
        the full prefix."""
        if self._prefix_cache is None:
            return
        prompt = handle.request.prompt
        C = self.prefill_chunk
        n_chunks = len(prompt) // C
        if n_chunks and not all(
            self._prefix_cache.contains(prompt, j)
            for j in range(1, n_chunks + 1)
        ):
            if self.kv_layout == "paged":
                bpc = C // self.page_size  # blocks per chunk
                pages = self.slots.bank(slot, n_chunks * bpc)
                for j in range(1, n_chunks + 1):
                    self._prefix_cache.store_pages(
                        prompt, j, pages[(j - 1) * bpc : j * bpc]
                    )
            else:
                spans = self.slots.extract_spans(slot, C, n_chunks)
                for j, span in enumerate(spans, start=1):
                    self._prefix_cache.store(prompt, j, span)

    def _on_prefill_fault(self, exc: Exception) -> None:
        """A chunk-prefill dispatch failed: fail ONLY the slots mid-prefill
        (retryable error to those clients) and keep everything else — the
        chunk program never donates the cache, so the pre-chunk buffers
        (including every decoding slot's rows) are intact and nothing needs
        a rebuild. Unlike decode faults this does not feed the breaker:
        blast radius is per-request and bounded, and the shared decode
        executable was never implicated."""
        self.stats["prefill_faults"] += 1
        now = self.now()
        failed = sorted(self._prefilling)
        for slot in failed:
            job = self._prefilling.pop(slot)
            job.handle._finish(
                FAILED,
                now,
                error=f"prefill chunk failed (retryable): {exc!r}",
                retryable=True,
            )
        self.slots.release(failed)
        self._event("prefill_fault", error=repr(exc), slots_failed=len(failed))

    def _retire(self, finished: List[int]) -> None:
        self.slots.release(finished)
        for slot in finished:
            self._active[slot] = None

    def _sweep_active(self) -> None:
        """Drop cancelled / past-deadline slots BEFORE the tick so their
        token is neither computed against a dead deadline nor emitted."""
        now = self.now()
        finished = []
        for slot, act in enumerate(self._active):
            if act is None:
                continue
            if act.handle._cancel.is_set():
                self.stats["cancelled"] += 1
                act.handle._finish(CANCELLED, now)
                finished.append(slot)
            elif (
                act.handle.request.deadline is not None
                and now > act.handle.request.deadline
            ):
                self.stats["expired_decoding"] += 1
                act.handle._finish(EXPIRED, now, error="deadline expired mid-decode")
                finished.append(slot)
        self._retire(finished)
        # mid-prefill slots honor cancel/deadline at the same tick boundary
        dropped = []
        for slot, job in self._prefilling.items():
            if job.handle._cancel.is_set():
                self.stats["cancelled"] += 1
                job.handle._finish(CANCELLED, now)
            elif (
                job.handle.request.deadline is not None
                and now > job.handle.request.deadline
            ):
                # its own counter, not expired_decoding: an operator tuning
                # against prefill-phase expiries (prompt length vs chunk
                # budget) must not be steered at decode budgets
                self.stats["expired_prefilling"] += 1
                job.handle._finish(
                    EXPIRED, now, error="deadline expired during prefill"
                )
            else:
                continue
            dropped.append(slot)
        for slot in dropped:
            del self._prefilling[slot]
        self.slots.release(dropped)

    def _sweep_queue(self) -> None:
        """Finish cancelled / past-deadline requests still WAITING, every
        tick — not only when a free slot lets ``_admit`` pop them. With all
        slots busy on long generations, a queued request's deadline (and
        ``cancel()``'s next-tick promise) must not wait for a slot to free."""
        now = self.now()
        with self._lock:
            kept: List[RequestHandle] = []
            dropped = False
            for cand in self._queue:
                if cand._cancel.is_set():
                    self.stats["cancelled"] += 1
                    cand._finish(CANCELLED, now)
                    dropped = True
                elif cand.request.deadline is not None and now > cand.request.deadline:
                    self.stats["expired_queued"] += 1
                    cand._finish(EXPIRED, now, error="deadline expired in queue")
                    dropped = True
                else:
                    kept.append(cand)
            if dropped:
                self._queue.rebuild(kept)

    # graftlint: hot-path
    # graftlint: supervised-seam
    def step(self) -> bool:
        """One scheduler tick: swap-in reload, sweep, admit, chunk-prefill
        budget (one chunk per mid-prefill slot, batched), supervised fused
        decode, emit, retire. Returns False when there was nothing to do."""
        # staged profile windows start/advance/stop here — the tick thread
        # owns the process-global jax profiler. Keyed on the BUSY-tick
        # counter (self._tick), so "capture N ticks" means N ticks of real
        # work, not N idle spins of the scheduler loop
        self._profiler.poll(self._tick)
        tr = self.tracer
        tick_idx = self._tick
        t_tick = self.now() if tr.enabled else 0.0
        self._swap_pending_params()
        self._sweep_queue()
        self._sweep_active()
        self._service_migrations()
        self._service_imports()
        self._prefill_work = False
        self._admit()
        ran_prefill = self._prefill_tick() if self.prefill_chunk else False
        if self.kv_layout == "paged":
            self._grow_decode_pages()
        # an idle DEGRADED engine still runs the fused step as a self-probe
        # (all rows parked, outputs discarded): without it, a load balancer
        # honoring the 503 starves the engine of the clean tick it needs to
        # close the breaker, and the replica would stay DEGRADED forever
        probe = self._breaker.open and self.active_count == 0
        if self.active_count == 0 and not probe:
            if ran_prefill:
                # prefill-only tick: nothing decodes yet, but the tick did
                # real work and the loop must not sleep
                if tr.enabled:
                    tr.add("tick", "engine", t_tick, self.now(),
                           {"tick": tick_idx, "phase": "prefill_only"})
                self.flight.tick({
                    "tick": tick_idx, "prefilling": len(self._prefilling),
                    "active": 0, "queued": len(self._queue), "emitted": 0,
                })
                self._tick += 1
                return True
            return False

        # -- supervised region: a fault here poisons AT MOST this tick's
        # active slots, never the scheduler thread (run() stays alive and
        # queued requests admit on the next tick)
        t_dec = self.now() if tr.enabled else 0.0
        try:
            if self._chaos is not None:
                self._chaos.on_tick(self._tick)
            if self.kv_layout == "paged":
                # one batched push of every block-table change this tick
                # (admissions, growth, retirements) before the fused step
                # reads the device tables
                self.slots.flush_tables()
            if self.draft_k and self._spec_enabled:
                blocks, n_emits, bad_rows = self._dispatch_spec()
            else:
                if self.fused_tail:
                    fused_args = (
                        self.model,
                        self.sampling,
                        self.params,
                        self._last_logits,
                        self.slots.cache,
                        self._gen_mask,
                        self._rngs,
                    )
                    # skip model (0) + params (2) — engine-lifetime
                    # constants; sampling statics + cache/logits/mask/rng
                    # shapes remain
                    self._ds_decode.observe(fused_args[1], *fused_args[3:])
                    if self._paged_kernel:
                        # the paged kernel's compiled family is selected by
                        # the table/pool shapes inside the cache tree plus
                        # the decode window — pin them at bound 1
                        self._ds_paged.observe(
                            fused_args[4], 1 + self.draft_k
                        )
                    token, self._last_logits, self.slots.cache, self._gen_mask, self._rngs, bad = _in_mesh(
                        self.mesh, self._fused, *fused_args
                    )
                else:
                    token, bad = self._dispatch_defused()
                if self._chaos is not None:
                    # injected NaNs land AFTER the step, so re-run the same
                    # predicate over the poisoned logits — injected and organic
                    # NaNs are judged by the identical criterion (the extra
                    # dispatch is chaos-only; the healthy path stays at one)
                    self._last_logits = self._chaos.poison_logits(
                        self._tick, self._last_logits
                    )
                    bad = _in_mesh(self.mesh, nonfinite_rows, self._last_logits)
                # graftlint: allow[host-sync-in-hot-path] reason=THE designed per-tick sync — one coalesced device_get of token + poison mask (PR 2's one-sync budget); every other read rides it
                tokens, bad_rows = jax.device_get((token, bad))
                blocks = [[int(t)] for t in tokens.tolist()]
                n_emits = [1] * self.n_slots
        except CompileFamilyExceeded:
            # strict-mode sanitizer trip: surface the signature listing to
            # the test harness instead of feeding it to the breaker as an
            # opaque tick fault (non-strict mode never raises — it warns)
            raise
        except Exception as exc:
            # ring entry FIRST: a breaker trip inside _on_tick_fault dumps
            # the recorder, and the dump must contain the tick that tripped
            self.flight.tick({
                "tick": tick_idx, "fault": True, "error": repr(exc),
                "queued": len(self._queue),
            })
            self._on_tick_fault(exc)
            self._tick += 1
            return True
        if tr.enabled:
            # decode_step covers dispatch + the device_get sync — the
            # on-device milliseconds of this tick
            tr.add("decode_step", "engine", t_dec, self.now(),
                   {"tick": tick_idx, "active": self.active_count,
                    "spec": bool(self.draft_k)})
        if self._breaker.record_clean():
            self._rebuilds_since_recovery = 0
            if not self.draining:
                self.lifecycle.to(READY, reason="breaker closed after clean tick")
            self._event("breaker_closed")

        now = self.now()
        finished: List[int] = []
        poisoned: List[int] = []
        ttft_new: List[tuple] = []  # (sample_s, qos_class)
        itl_new: List[tuple] = []
        tokens_before = self.stats["tokens_out"]
        paged_ledger = self.kv_layout == "paged"
        for slot, act in enumerate(self._active):
            if act is None:
                continue
            qos_cls = self.qos.normalize(act.handle.request.qos)
            toks = blocks[slot][: n_emits[slot]]
            # cost ledger: one decode tick held, at this slot's current KV
            # page footprint (pages x ticks is the capacity-time integral a
            # tenant actually consumed; slab slots have no page unit — 0)
            act.handle.ledger["decode_ticks"] += 1
            if paged_ledger:
                act.handle.ledger["pages_held_ticks"] += (
                    self.slots.alloc_blocks[slot]
                )
            if act.emitted == 0:
                ttft_new.append((now - act.handle.submitted_at, qos_cls))
            elif act.last_emit_at is not None:
                # a speculative tick delivers its accepted block in one
                # burst; one AMORTIZED sample per token keeps the ITL
                # percentiles honest about per-token latency (n_emit = 1
                # degenerates to the classic one-sample-per-tick)
                gap = now - act.last_emit_at
                itl_new.extend([(gap / len(toks), qos_cls)] * len(toks))
            # the block's first token was sampled from the PREVIOUS (finite)
            # logits, so it is valid even when the new logits went bad —
            # emit it, then retire the poisoned slot with a retryable error
            # (a bad row's n_emit is already clamped to that first token:
            # drafts "verified" by garbage logits are never emitted)
            done_now = False
            for t in toks:
                act.handle._emit(int(t), now)
                act.emitted += 1
                act.last_emit_at = now
                self.stats["tokens_out"] += 1
                act.handle.ledger["tokens_out"] += 1
                hit_eos = (
                    self.eos_token_id is not None and int(t) == self.eos_token_id
                )
                if hit_eos or act.emitted >= act.handle.request.max_new_tokens:
                    # completion outranks the poison flag: the tokens
                    # emitted so far all trace to finite logits, so a
                    # request finishing now delivered a fully valid output
                    act.handle._finish(DONE, now)
                    self.stats["completed"] += 1
                    finished.append(slot)
                    done_now = True
                    break
            if not done_now and bool(bad_rows[slot]):
                act.handle._finish(
                    FAILED, now,
                    error="non-finite logits in decode (retryable)",
                    retryable=True,
                )
                self.stats["poisoned_slots"] += 1
                poisoned.append(slot)
                finished.append(slot)
            elif not done_now and act.handle.overflowed:
                # the STREAMING consumer stopped draining past the emit
                # buffer bound: stop paying slot/page capacity for a
                # reader that went away. Retryable — the done event always
                # delivers, so a recovered client re-submits cleanly.
                act.handle._finish(
                    FAILED, now,
                    error=(
                        "client stalled mid-stream; emit buffer "
                        "overflowed (retryable)"
                    ),
                    retryable=True,
                )
                self.stats["stalled_streams"] += 1
                finished.append(slot)
                self._event("stalled_stream", request_id=act.handle.rid)
        if any(bad_rows):
            # zero EVERY bad row (poisoned-and-retired or finished-anyway)
            # so a parked slot never feeds NaN back into the next tick's
            # sample — retirement alone leaves the row in place
            keep = jnp.asarray([not b for b in bad_rows], jnp.bool_)
            self._last_logits = jnp.where(keep[:, None], self._last_logits, 0.0)
        if poisoned:
            self._event("poisoned_slots", slots=len(poisoned))
        # histograms carry their own micro-locks — no scheduler lock, and a
        # concurrent /metrics scrape reads bucket counts, never a sample list
        for sample, cls in ttft_new:
            self._h_ttft.observe(sample)
            self._h_ttft_class[cls].observe(sample)
        for sample, cls in itl_new:
            self._h_itl.observe(sample)
            self._h_itl_class[cls].observe(sample)
            if not self._prefill_work:
                # per-phase attribution: this tick ran no prefill work
                # (chunk, span copy, or one-shot admission), so these
                # samples are the pure-decode ITL floor
                self._h_itl_decode.observe(sample)
            self._itl_ewma.update(sample)
        self._retire(finished)

        emitted_total = self.stats["tokens_out"] - tokens_before
        if tr.enabled:
            tr.add("emit", "engine", now, self.now(),
                   {"tick": tick_idx, "finished": len(finished)})
            tr.add("tick", "engine", t_tick, self.now(), {"tick": tick_idx})
        self.flight.tick({
            "tick": tick_idx, "active": self.active_count,
            "prefilling": len(self._prefilling), "queued": len(self._queue),
            "emitted": emitted_total, "finished": len(finished),
            "poisoned": len(poisoned),
        })
        self._tick += 1
        if (
            self.metrics is not None
            and self.metrics_interval
            and self._tick % self.metrics_interval == 0
        ):
            self.metrics.log(self.metrics_snapshot(), step=self._tick, prefix="serve")
        return not probe

    # --------------------------------------------------- speculative decode

    # graftlint: hot-path
    def _dispatch_spec(self):
        """Run the speculative fused step for this tick: host-propose K
        draft tokens per decoding slot (prompt-lookup over the slot's own
        prompt + emitted history, or the engine's pluggable ``draft_fn``),
        verify them all in ONE batched forward, and return per-slot emit
        blocks. A row whose verify logits went non-finite is clamped to its
        first token (sampled from the previous, finite distribution) — the
        plain step's exact poison semantics."""
        K, S = self.draft_k, self.n_slots
        V = self.cfg.vocab_size
        drafts = [[0] * K for _ in range(S)]
        active = [a is not None for a in self._active]
        for slot, act in enumerate(self._active):
            if act is None:
                continue
            hist = list(act.handle.request.prompt) + act.handle.tokens
            d = [int(t) for t in self.draft_fn(hist, K)]
            # clamp a misbehaving custom draft_fn: wrong-length or
            # out-of-vocab drafts must degrade acceptance, not crash a tick
            drafts[slot] = [t % V for t in d[:K]] + [0] * (K - len(d))
        spec_args = (
            self.model,
            self.sampling,
            K,
            self.params,
            self._last_logits,
            self.slots.cache,
            self._gen_mask,
            self._rngs,
            jnp.asarray(drafts, jnp.int32),
            self._veto,
            jnp.asarray(active, jnp.bool_),
        )
        # skip model (0) + params (3) — engine-lifetime constants
        self._ds_spec.observe(*spec_args[1:3], *spec_args[4:])
        if self._paged_kernel:
            self._ds_paged.observe(spec_args[5], 1 + K)
        x, n_acc, self._last_logits, self.slots.cache, self._gen_mask, self._rngs, self._veto, bad = _in_mesh(
            self.mesh, self._spec, *spec_args
        )
        if self._chaos is not None:
            self._last_logits = self._chaos.poison_logits(
                self._tick, self._last_logits
            )
            bad = bad | _in_mesh(self.mesh, nonfinite_rows, self._last_logits)
        # graftlint: allow[host-sync-in-hot-path] reason=THE designed per-tick sync of the speculative path — one coalesced device_get of the accepted block + counts + poison mask
        xs, n_accs, bad_rows = jax.device_get((x, n_acc, bad))
        self.stats["spec_ticks"] += 1
        blocks = [row.tolist() for row in xs]
        n_emits = [1] * S
        for slot in range(S):
            if not active[slot]:
                continue
            self.stats["draft_tokens"] += K
            ledger = self._active[slot].handle.ledger
            ledger["draft_tokens"] += K
            if not bool(bad_rows[slot]):
                acc = int(n_accs[slot])
                self.stats["accepted_tokens"] += acc
                ledger["accepted_tokens"] += acc
                n_emits[slot] = 1 + acc
        return blocks, n_emits, bad_rows

    # graftlint: hot-path
    def _dispatch_defused(self):
        """The fused-tail A/B CONTROL (``fused_tail=False``): the same tick
        math as the fused step, split into a standalone sample dispatch and
        a forward-only dispatch — what every token cost before sampling
        moved into the decode program. Trajectories stay byte-identical to
        the fused path (identical ops, identical rng split order); only the
        dispatch count (and the [S] token round-trip between the two
        programs) differs, which is exactly what the bench's
        ``no_fused_tail`` arm prices."""
        tail_args = (self.sampling, self._last_logits, self._gen_mask, self._rngs)
        self._ds_sample.observe(*tail_args)
        token, self._gen_mask, self._rngs = _in_mesh(
            self.mesh, self._sample_tail, *tail_args
        )
        fwd_args = (self.model, self.params, token, self.slots.cache)
        self._ds_decode.observe(fwd_args[2], fwd_args[3])
        self._last_logits, self.slots.cache, bad = _in_mesh(
            self.mesh, self._forward_only, *fwd_args
        )
        return token, bad

    # ------------------------------------- transferable streams (migration)

    @property
    def migrations_in_flight(self) -> int:
        """Streams exported and awaiting the ship acknowledgement."""
        return self._migrations_in_flight

    def request_migration(self, request_id: str, target: str) -> bool:
        """Ask the tick thread to migrate the live stream ``request_id`` to
        ``target`` (a replica base URL). Thread-safe; returns False when no
        live stream carries that id (the caller maps it to 404) or when
        this engine has nothing transferable (slab layout — a 202 here
        would promise a migration that can never be serviced). The export
        itself happens between ticks — device state stays tick-thread-owned."""
        if self.kv_layout != "paged":
            return False
        # snapshot under the GIL (list() of a dict/list is one C-level op)
        # — the tick thread mutates both containers concurrently, and bare
        # iteration from this HTTP thread could see "changed size"
        active = list(self._active)
        prefilling = list(self._prefilling.values())
        found = any(
            a is not None and a.handle.rid == request_id for a in active
        ) or any(j.handle.rid == request_id for j in prefilling)
        if not found:
            return False
        with self._lock:
            self._migrate_requests[request_id] = target
        return True

    def request_migrate_all(self, target: str) -> int:
        """Migrate EVERY live stream to ``target`` (scale-down / drain
        upgrade). Returns how many streams were tagged (0 on a slab
        engine: pages are the transfer unit, so there is nothing to ship
        and the caller's classic drain covers it)."""
        if self.kv_layout != "paged":
            return 0
        n = sum(1 for a in list(self._active) if a is not None) + len(
            self._prefilling
        )
        if n:
            with self._lock:
                self._migrate_requests["*"] = target
        return n

    # graftlint: hot-path
    def _service_migrations(self) -> None:
        """Tick-thread side of migration SEND: export each tagged slot's
        pages + decode carry, release the slot, and hand the payload to the
        shipper. The handle stays unfinished (status ``running``) until the
        ship acknowledges — success finishes it ``migrated`` (the router
        attaches at the target, zero tokens replayed), failure finishes it
        retryably (the router falls back to re-dispatch-and-recompute)."""
        if self.kv_layout != "paged":
            return
        with self._lock:
            reqs, self._migrate_requests = self._migrate_requests, {}
        if not reqs:
            return
        every = reqs.pop("*", None)
        jobs: List[tuple] = []  # (slot, handle, is_prefill, target)
        for slot, act in enumerate(self._active):
            if act is None:
                continue
            target = reqs.get(act.handle.rid, every)
            if target:
                jobs.append((slot, act.handle, False, target))
        for slot, job in list(self._prefilling.items()):
            target = reqs.get(job.handle.rid, every)
            if target:
                jobs.append((slot, job.handle, True, target))
        for slot, handle, is_prefill, target in jobs:
            try:
                if is_prefill:
                    payload = self._export_prefill(slot)
                else:
                    payload = self._export_decoding(slot)
            except Exception as exc:  # a bad export fails ONLY this stream, retryably
                self._detach_slot(slot, is_prefill)
                self._migration_failed(handle, f"export failed: {exc!r}")
                continue
            self._detach_slot(slot, is_prefill)
            with self._lock:
                self._migrating[handle.id] = handle
                self._migrations_in_flight += 1
            self._ship(payload, target, handle)

    def _detach_slot(self, slot: int, is_prefill: bool) -> None:
        """Free the slot WITHOUT finishing its handle (the handle's fate is
        the ship's to decide)."""
        if is_prefill:
            self._prefilling.pop(slot, None)
        else:
            self._active[slot] = None
        self.slots.release([slot])

    def _stream_meta(self, handle: RequestHandle, consumed: List[int],
                     remaining: int) -> Dict[str, Any]:
        req = handle.request
        deadline_s = (
            max(0.05, req.deadline - self.now())
            if req.deadline is not None else None
        )
        return {
            "request_id": handle.rid,
            "prompt": [int(t) for t in consumed],
            "max_new_tokens": int(remaining),
            "seed": int(req.seed),
            "deadline_s": deadline_s,
            "draft_k": self.draft_k,
            # cost-ledger carry: counters + the ms already spent here (the
            # handle is still LIVE, so wall time accrues to now), so the
            # destination's terminal event reports the CUMULATIVE cost of
            # the whole stream, not just its final hop
            "ledger": handle.ledger_snapshot(now=self.now()),
            "hop": handle.trace_hop,
        }

    # graftlint: hot-path
    def _export_decoding(self, slot: int) -> Dict[str, Any]:
        """Payload for a mid-decode stream: pages covering every consumed
        position [0, prompt + emitted) plus the decode carry (last_logits /
        gen_mask / rng / veto rows) — the destination continues the exact
        trajectory with zero recompute."""
        act = self._active[slot]
        handle = act.handle
        consumed = list(handle.request.prompt) + [int(t) for t in handle.tokens]
        cursor = len(consumed)
        span = self.slots.export_page_span(slot, cursor)
        # graftlint: allow[host-sync-in-hot-path] reason=THE designed migration-send sync — one coalesced device_get of the slot's decode carry, only when a stream migrates
        row, mask_row, key, veto = jax.device_get((
            self._last_logits[slot], self._gen_mask[slot],
            self._rngs[slot], self._veto[slot],
        ))
        meta = self._stream_meta(
            handle, consumed,
            handle.request.max_new_tokens - len(handle.tokens),
        )
        leaves = dict(span["leaves"])
        leaves["carry/last_logits"] = row
        leaves["carry/gen_mask"] = mask_row
        leaves["carry/rng"] = key
        return {
            **meta,
            "kind": "decode",
            "veto": int(veto),
            "page_size": span["page_size"],
            "n_blocks": span["n_blocks"],
            "n_tokens": span["n_tokens"],
            "leaves": leaves,
        }

    def _export_prefill(self, slot: int) -> Dict[str, Any]:
        """Payload for a mid-prefill stream: pages covering [0, fill) and
        the fill cursor — the destination finishes the remaining chunks
        (deterministic forward: bit-identical to never having moved)."""
        job = self._prefilling[slot]
        span = self.slots.export_page_span(slot, job.fill)
        meta = self._stream_meta(
            job.handle, list(job.handle.request.prompt),
            job.handle.request.max_new_tokens,
        )
        return {
            **meta,
            "kind": "prefill",
            "fill": int(job.fill),
            "page_size": span["page_size"],
            "n_blocks": span["n_blocks"],
            "n_tokens": span["n_tokens"],
            "leaves": dict(span["leaves"]),
        }

    def _ship(self, payload: Dict[str, Any], target: str,
              handle: RequestHandle) -> None:
        shipper = self.page_shipper
        if shipper is None:
            self._migration_failed(handle, "no page shipper configured")
            return

        def on_done(err: Optional[str]) -> None:
            if err is None:
                self._migration_done(handle, target)
            else:
                self._migration_failed(handle, err)

        try:
            shipper(payload, target, on_done)
        except Exception as exc:  # a shipper crash degrades to the recompute fallback
            self._migration_failed(handle, f"shipper raised: {exc!r}")

    def _migration_done(self, handle: RequestHandle, target: str) -> None:
        # runs on the SHIPPER's thread: every read-modify-write here races
        # the tick thread's increments, so all bookkeeping sits under the
        # engine lock (the gauge feeds the router's placement — drift
        # would be permanent)
        with self._lock:
            self._migrating.pop(handle.id, None)
            self._migrations_in_flight = max(0, self._migrations_in_flight - 1)
            if handle.status in _FINISHED:
                return  # an abort beat the ship ack; the client already heard
            handle.migrated_to = target
            self.stats["migrations_out"] += 1
            if handle.request.prefill_to is not None:
                self.stats["prefill_handoffs"] += 1
        handle._finish(MIGRATED, self.now())
        self._event(
            "stream_migrated", target=target, request_id=handle.rid,
            tokens_done=len(handle.tokens),
        )

    def _migration_failed(self, handle: RequestHandle, err: str) -> None:
        with self._lock:
            self._migrating.pop(handle.id, None)
            self._migrations_in_flight = max(
                0, self._migrations_in_flight - 1
            )
            finished = handle.status in _FINISHED
            if not finished:
                self.stats["migration_failures"] += 1
        if finished:
            return  # an abort beat the ship ack
        self._event("migration_failed", error=err, request_id=handle.rid)
        # post-mortem window: a failed ship is exactly when an operator
        # asks "what was the fleet doing" — dump while the ring still
        # holds the ticks around the export
        self.flight.dump(
            "migration_failed",
            extra={"error": err, "request_id": handle.rid},
        )
        handle._finish(
            FAILED, self.now(),
            error=f"migration failed: {err} (retryable)", retryable=True,
        )

    # ---- receive side ----------------------------------------------------

    @staticmethod
    def _validate_import_payload(payload) -> Optional[str]:
        """Structural check of a migrated-stream payload — everything the
        tick thread will later subscript must exist and parse, so a bad
        peer costs one rejected import, not the scheduler thread."""
        if not isinstance(payload, dict):
            return "payload must be a dict"
        for key in ("kind", "prompt", "max_new_tokens", "page_size",
                    "n_blocks", "leaves"):
            if key not in payload:
                return f"missing field {key!r}"
        if payload["kind"] not in ("decode", "prefill"):
            return f"unknown kind {payload['kind']!r}"
        if not isinstance(payload["leaves"], dict):
            return "leaves must be a dict"
        try:
            int(payload["max_new_tokens"])
            int(payload["page_size"])
            int(payload["n_blocks"])
            int(payload.get("veto", -1))
            [int(t) for t in payload["prompt"]]
            if payload.get("deadline_s") is not None:
                float(payload["deadline_s"])
            if payload["kind"] == "prefill":
                int(payload["fill"])
        except (TypeError, ValueError, KeyError) as exc:
            return f"unparseable field: {exc!r}"
        if payload["kind"] == "decode":
            for leaf in ("carry/last_logits", "carry/gen_mask", "carry/rng"):
                if leaf not in payload["leaves"]:
                    return f"missing decode carry leaf {leaf!r}"
        return None

    def import_stream(self, payload: Dict[str, Any]) -> RequestHandle:
        """Accept a migrated stream (any thread): validate, then queue it
        for the tick thread to place — device state stays tick-owned. The
        returned handle streams the CONTINUATION (only new tokens; the
        client already holds the rest). A handle that could not be accepted
        comes back already finished (rejected/failed, retryable where the
        condition is transient)."""
        now = self.now()
        # structural validation FIRST: a version-skewed or malformed peer
        # payload must become a clean retryable rejection here, never a
        # KeyError on the tick thread (which would abort the whole engine)
        structural = self._validate_import_payload(payload)
        if structural is not None:
            handle = RequestHandle(
                Request([0], 1), next(self._ids), now,
                request_id=payload.get("request_id")
                if isinstance(payload, dict) else None,
            )
            handle._tracer = self.tracer
            handle._finish(
                REJECTED, now, error=f"bad import payload: {structural}",
                retryable=True,
            )
            return handle
        deadline = (
            now + float(payload["deadline_s"])
            if payload.get("deadline_s") is not None else None
        )
        request = Request(
            [int(t) for t in payload["prompt"]],
            int(payload["max_new_tokens"]),
            int(payload.get("seed", 0)),
            deadline,
        )
        handle = RequestHandle(
            request, next(self._ids), now,
            request_id=payload.get("request_id"),
        )
        handle._tracer = self.tracer
        self._seed_imported_ledger(handle, payload)
        if self.role == "prefill":
            handle._finish(
                REJECTED, now,
                error="prefill-role replica cannot import streams",
            )
            return handle
        if self.kv_layout != "paged":
            handle._finish(
                REJECTED, now, error="import requires kv_layout='paged'",
            )
            return handle
        if int(payload.get("draft_k", 0)) != self.draft_k:
            # the veto/rewind carry is draft_k-shaped; a mismatched fleet
            # config must degrade to the recompute fallback, not corrupt
            handle._finish(
                REJECTED, now,
                error=(
                    f"draft_k mismatch: stream {payload.get('draft_k')}, "
                    f"replica {self.draft_k}"
                ),
                retryable=True,
            )
            return handle
        invalid = self._validate(request)
        if invalid is not None:
            handle._finish(REJECTED, now, error=invalid)
            return handle
        with self._lock:
            if self._dead is not None:
                handle._finish(FAILED, now, error=self._dead)
                return handle
            if self.lifecycle.state == DRAINING:
                handle._finish(
                    REJECTED, now, error="server draining; retry elsewhere",
                    retryable=True, retry_after=1.0,
                )
                return handle
            if len(self._pending_imports) >= self.max_queue:
                # each queued import pins a whole deserialized span in host
                # memory — the same backpressure bound as submit(), so a
                # fleet-wide migrate_all onto one target gets honest 503s
                # (shippers fail over) instead of ballooning this replica
                handle._finish(
                    REJECTED, now,
                    error=f"import queue full ({self.max_queue} waiting)",
                    retryable=True, retry_after=1.0,
                )
                return handle
            self._pending_imports.append((handle, payload))
        return handle

    @staticmethod
    def _seed_imported_ledger(handle: RequestHandle, payload: Dict[str, Any]) -> None:
        """Continue the shipped stream's cumulative cost ledger: counters
        carry over verbatim, the source's ms split becomes this handle's
        base, and the page crossing itself counts as one migration.
        Defensive coercion — a version-skewed peer's ledger must degrade
        to zeros, never fault the import."""
        led = payload.get("ledger")
        if isinstance(led, dict):
            for key in handle.ledger:
                try:
                    handle.ledger[key] = int(led.get(key, 0) or 0)
                except (TypeError, ValueError):
                    pass
            for key in handle._ledger_ms_base:
                try:
                    handle._ledger_ms_base[key] = float(led.get(key, 0.0) or 0.0)
                except (TypeError, ValueError):
                    pass
        handle.ledger["migrations"] += 1
        hop = payload.get("hop")
        if hop is not None:
            try:
                # the attach dispatch is the NEXT hop after the ship
                handle.trace_hop = int(hop) + 1
            except (TypeError, ValueError):
                pass

    # graftlint: hot-path
    def _service_imports(self) -> None:
        """Tick-thread side of migration RECEIVE: place queued imports —
        allocate pages, scatter the span in, install the decode carry (or
        re-arm the prefill job), and continue. Imports outrank normal
        admission (their tokens are already paid for elsewhere); one that
        cannot fit yet waits at the head, FIFO, exactly like paged
        admission backpressure. Entries are POPPED under the lock (never
        peeked): a concurrent ``begin_drain`` snapshot can therefore never
        hold the same handle this thread is placing — the requeue path
        re-checks drain state under the same lock, so a drained handle is
        finished exactly once, by exactly one side."""
        while True:
            with self._lock:
                if not self._pending_imports:
                    return
                handle, payload = self._pending_imports.popleft()
            now = self.now()
            if handle.status in _FINISHED:
                continue  # an abort beat us to it; nothing to place
            if handle._cancel.is_set():
                self.stats["cancelled"] += 1
                handle._finish(CANCELLED, now)
                continue
            if (
                handle.request.deadline is not None
                and now > handle.request.deadline
            ):
                self.stats["expired_queued"] += 1
                handle._finish(
                    EXPIRED, now, error="deadline expired awaiting import"
                )
                continue
            wait = not self.slots.free_count
            if not wait:
                total_blocks = self.slots.blocks_for(
                    self._total_need_tokens(handle.request)
                )
                short = total_blocks - self.slots.pool.available
                if short > 0 and self._prefix_cache is not None and len(
                    self._prefix_cache
                ):
                    self.stats["page_faults"] += 1
                    self.stats["pages_reclaimed"] += self._prefix_cache.reclaim(
                        short
                    )
                wait = total_blocks > self.slots.pool.available
            if not wait and self._place_import(handle, payload):
                continue
            # cannot place yet (no slot / pool pressure / pool raced away):
            # back to the HEAD — unless a drain/abort landed meanwhile, in
            # which case the queue we'd rejoin has already been flushed
            with self._lock:
                if self._dead is None and self.lifecycle.state != DRAINING:
                    self._pending_imports.appendleft((handle, payload))
                    return
            handle._finish(
                REJECTED, now, error="server draining; retry elsewhere",
                retryable=True, retry_after=1.0,
            )
            return

    # graftlint: hot-path
    def _place_import(self, handle: RequestHandle, payload: Dict[str, Any]) -> bool:
        """Materialize one import into a slot. True when the handle left
        the pending queue (placed OR terminally failed); False to retry
        next tick."""
        slot = self.slots.acquire()
        now = self.now()
        # graftlint: allow[host-sync-in-hot-path] reason=wire-payload fields are host ints/numpy (json header + frombuffer), never device values
        fill, veto_val, n_blocks = int(payload.get("fill", 0)), int(payload.get("veto", -1)), int(payload["n_blocks"])
        try:
            ok = self.slots.import_page_span(slot, {
                "page_size": payload["page_size"],
                "n_blocks": n_blocks,
                "leaves": {
                    k: v for k, v in payload["leaves"].items()
                    if not k.startswith("carry/")
                },
            })
        except Exception as exc:  # geometry/dtype skew fails ONE import, never the tick thread
            self.slots.release([slot])
            handle._finish(
                FAILED, now, error=f"import rejected: {exc}", retryable=True,
            )
            return True
        if not ok:
            self.slots.release([slot])
            return False  # pool raced away; retry next tick
        try:
            self.slots.reserve(slot, self._total_need_tokens(handle.request))
            handle.status = RUNNING
            handle.admitted_at = now
            self._h_queue_wait.observe(now - handle.submitted_at)
            if payload["kind"] == "prefill":
                self.slots.set_cursor(slot, fill)
                self._prefilling[slot] = _PrefillJob(handle, fill=fill)
            else:
                leaves = payload["leaves"]
                self.slots.set_cursor(slot, len(handle.request.prompt))
                args = (
                    self._last_logits, self._gen_mask, self._rngs,
                    self._veto, jnp.int32(slot),
                    jnp.asarray(leaves["carry/last_logits"], jnp.float32),
                    jnp.asarray(leaves["carry/gen_mask"], jnp.bool_),
                    jnp.asarray(leaves["carry/rng"], jnp.uint32),
                    jnp.int32(veto_val),
                )
                self._last_logits, self._gen_mask, self._rngs, self._veto = _in_mesh(
                    self.mesh, _install_import, *args
                )
                handle.prefill_done_at = now
                self._active[slot] = _ActiveSlot(handle)
                self.stats["peak_occupancy"] = max(
                    self.stats["peak_occupancy"], self.active_count
                )
        except Exception as exc:  # bad carry shapes fail ONE import, never the tick thread
            self._prefilling.pop(slot, None)
            self._active[slot] = None
            self.slots.release([slot])
            handle._finish(
                FAILED, now, error=f"import install failed: {exc!r}",
                retryable=True,
            )
            return True
        self.stats["migrations_in"] += 1
        self._event(
            "stream_imported", request_id=handle.rid, kind=payload["kind"],
            blocks=n_blocks,
        )
        return True

    def _grow_decode_pages(self) -> None:
        """Paged: extend each decoding slot's block table to cover this
        tick's writes (cursor + 1, plus the draft window when speculating),
        with a copy-on-write guard on the first written block (chunk/page
        alignment makes a shared cursor page unreachable; the guard keeps
        that a checked invariant). A slot the pool genuinely cannot cover —
        reservations make that a bookkeeping bug — preempts retryably
        rather than corrupting a neighbor."""
        span = 1 + self.draft_k
        victims: List[int] = []
        for slot, act in enumerate(self._active):
            if act is None:
                continue
            cursor = len(act.handle.request.prompt) + act.emitted
            if not self._ensure_pages_or_reclaim(slot, cursor + span):
                victims.append(slot)
                continue
            if not self.slots.cow(slot, cursor // self.page_size):
                victims.append(slot)
        if victims:
            now = self.now()
            for slot in victims:
                self.stats["preemptions"] += 1
                self._active[slot].handle._finish(
                    FAILED, now,
                    error="KV page pool exhausted; request preempted (retryable)",
                    retryable=True,
                )
            self._retire(victims)
            self._event("page_preemption", slots=len(victims), phase="decode")

    # ------------------------------------------------------ tick supervision

    def _event(self, name: str, **fields) -> None:
        """Resilience incident -> the same JSONL/wandb timeline the training
        stack writes (MetricsLogger.event), keyed by scheduler tick — and
        into the flight recorder's ring, so a later dump carries the event
        context even when no MetricsLogger is attached."""
        self.flight.event(name, tick=self._tick, **fields)
        if self.metrics is not None:
            self.metrics.event(name, step=self._tick, **fields)

    def _on_tick_fault(self, exc: Exception) -> None:
        """One decode tick failed: fail ONLY the slots it poisoned (their
        clients get a retryable error event), reallocate the device state
        the tick may have invalidated, and let the breaker escalate —
        DEGRADED + a freshly jitted step after ``threshold`` consecutive
        faults, a loud abort after ``max_rebuilds`` consecutive rebuilds."""
        self.stats["tick_faults"] += 1
        now = self.now()
        failed = [s for s, a in enumerate(self._active) if a is not None]
        for slot in failed:
            self._active[slot].handle._finish(
                FAILED, now,
                error=f"decode tick failed (retryable): {exc!r}",
                retryable=True,
            )
            # HOST-only cleanup — _retire would run the jitted index reset
            # over self.slots.cache, whose buffers the faulted (donating)
            # call may have deleted, re-raising INSIDE the fault handler and
            # killing the scheduler; _rebuild_device_state below replaces
            # the whole SlotKVCache (free list included) instead
            self._active[slot] = None
        # mid-prefill slots die with the tick too: the rebuild below
        # replaces the cache tree their half-filled rows live in (the
        # donating decode step made every shared buffer suspect)
        for slot in sorted(self._prefilling):
            job = self._prefilling.pop(slot)
            job.handle._finish(
                FAILED, now,
                error=f"decode tick failed (retryable): {exc!r}",
                retryable=True,
            )
            failed.append(slot)
        self._event("tick_fault", error=repr(exc), slots_failed=len(failed))
        if self._breaker.record_fault():
            self.stats["breaker_trips"] += 1
            self._rebuilds_since_recovery += 1
            if self._rebuilds_since_recovery > self.max_rebuilds:
                # a fault that survives this many CONSECUTIVE rebuilds is
                # structural, not transient — fail everything outstanding
                # (any driver, not just run(), must leave no handle hanging)
                # and escalate so the replica dies loudly; the orchestrator
                # owns restarts, not this loop
                reason = (
                    f"engine faulted through {self.max_rebuilds} rebuilds; "
                    f"last error: {exc!r}"
                )
                self._abort(reason)
                raise RuntimeError(reason) from exc
            self.lifecycle.to(
                DEGRADED,
                reason=f"breaker open after {self._breaker.threshold} faults",
            )
            self._event("breaker_trip", trips=self.stats["breaker_trips"])
            # post-mortem without verbose logging: the last N ticks of
            # context (summaries, events, span tail) land in the run dir
            # the moment the breaker opens, while the evidence is still in
            # the ring
            self.flight.dump(
                "breaker_open",
                extra={"error": repr(exc), "tick": self._tick,
                       "trips": self.stats["breaker_trips"]},
            )
            # the executable itself is suspect only once faults PERSIST:
            # swap in a privately jitted step on each trip (the spec step
            # is the same executable family — swap it with its twin)
            self._fused = _jit_fused_step()
            self._spec = _jit_spec_step()
            self._sample_tail, self._forward_only = _jit_defused_pair()
        # device buffers are suspect after EVERY fused-call fault, threshold
        # or not: the step donates logits/cache/masks/rngs, so an exception
        # after dispatch leaves them deleted or half-written — reusing them
        # would fail the NEXT tick's fresh admissions too (blast radius must
        # stay at THIS tick's slots)
        self._rebuild_device_state()

    def _rebuild_device_state(self) -> None:
        """Reallocate every device buffer the tick thread owns; nothing from
        a suspect tick is reused. Host state (queue, stats, lifecycle) and
        params are untouched. Paged: a fresh ``PagedKVCache`` means a fresh
        page pool AND a fresh allocator/refcount state — the pool
        reinitializes wholesale, never patched."""
        self.slots = self._make_slots()
        V = self.cfg.vocab_size
        self._last_logits = jnp.zeros((self.n_slots, V), jnp.float32)
        self._gen_mask = jnp.zeros((self.n_slots, V), jnp.bool_)
        self._rngs = jnp.stack([jax.random.PRNGKey(0)] * self.n_slots)
        self._veto = jnp.full((self.n_slots,), -1, jnp.int32)
        self._active = [None] * self.n_slots
        self._prefilling.clear()
        self._prefill_cache = None  # legacy template reallocates lazily
        if self._prefix_cache is not None:
            # conservative: cached entries trace to earlier, clean ticks,
            # but re-deriving which survived a faulted tick is not worth
            # wrong K/V if the reasoning ever rots — cold misses rebuild
            # the cache. Paged: the old index refcounts into the DEAD pool;
            # rebuild it against the fresh one instead of flushing into it.
            if self.kv_layout == "paged":
                self._prefix_cache = self._make_prefix_cache()
            else:
                self._prefix_cache.flush()
        self._event("engine_rebuilt")

    # ----------------------------------------------------------------- drain

    @property
    def draining(self) -> bool:
        return self.lifecycle.state == DRAINING

    def begin_drain(self, deadline_s: Optional[float] = 30.0) -> bool:
        """Stop admission and start finishing in-flight generations
        (SIGTERM maps here). Queued requests finish immediately as
        retryable rejections (their slot time belongs to requests already
        decoding); actives run to completion until ``deadline_s``, after
        which ``poll_drain`` force-finishes them. Thread-safe; idempotent."""
        now = self.now()
        if not self.lifecycle.to(DRAINING, reason="drain requested"):
            return False
        with self._lock:
            self._drain_started = now
            self._drain_deadline = (
                now + deadline_s if deadline_s is not None else None
            )
            queued = list(self._queue)
            self._queue.clear()
            pending, self._pending_imports = (
                list(self._pending_imports), deque()
            )
        queued = queued + [h for h, _ in pending]
        for handle in queued:
            self.stats["rejected_draining"] += 1
            handle._finish(
                REJECTED, now, error="server draining; retry elsewhere",
                retryable=True,
                retry_after=max(1.0, deadline_s) if deadline_s else 1.0,
            )
        self._event(
            "drain_begin", queued_rejected=len(queued), active=self.active_count
        )
        return True

    def poll_drain(self) -> bool:
        """Called between ticks while draining: True once the engine has
        fully drained (or the deadline forced it) and is STOPPED."""
        if not self.draining:
            return self.lifecycle.state == STOPPED
        now = self.now()
        if (
            self.active_count == 0
            and not self._prefilling
            and self.queue_depth == 0
            and not self._migrating
            and not self._pending_imports
        ):
            self._finish_drain(forced=0)
            return True
        if self._drain_deadline is not None and now > self._drain_deadline:
            forced = [s for s, a in enumerate(self._active) if a is not None]
            for slot in forced:
                self._active[slot].handle._finish(
                    FAILED, now,
                    error="drain deadline exceeded; generation force-finished",
                    retryable=True,
                )
            self._retire(forced)
            still_prefilling = sorted(self._prefilling)
            for slot in still_prefilling:
                job = self._prefilling.pop(slot)
                job.handle._finish(
                    FAILED, now,
                    error="drain deadline exceeded; generation force-finished",
                    retryable=True,
                )
            self.slots.release(still_prefilling)
            forced_total = len(forced) + len(still_prefilling)
            self.stats["drain_forced"] += forced_total
            self._finish_drain(forced=forced_total)
            return True
        return False

    def _finish_drain(self, forced: int) -> None:
        now = self.now()
        self.drain_latency_s = (
            now - self._drain_started if self._drain_started is not None else 0.0
        )
        with self._lock:
            self._dead = "engine drained (stopped)"
        self.lifecycle.to(STOPPED, reason="drained")
        self._event(
            "drain_done", forced=forced, drain_latency_s=self.drain_latency_s
        )
        self._profiler.abort()  # never leave the process-global trace running
        self.flight.dump(
            "drain",
            extra={"forced": forced, "drain_latency_s": self.drain_latency_s},
        )
        self.export_trace()

    # ------------------------------------------------------------ hot reload

    def reload_params(self, source) -> Dict[str, Any]:
        """Stage a standby param tree and swap it in between ticks — no slot
        is retired; in-flight generations continue on the new weights from
        their next token.

        ``source`` is a param tree or a zero-arg callable returning one
        (e.g. a lambda over ``checkpoint.import_params_msgpack``). Called
        OFF the tick thread (HTTP handler, SIGHUP thread): the load and the
        eval_shape validation happen here; the tick thread only flips a
        reference. A corrupt or mismatched artifact raises ``ReloadError``
        and the engine keeps serving the old weights, READY throughout."""
        try:
            tree = source() if callable(source) else source
            if self._chaos is not None:
                tree = self._chaos.corrupt_reload(tree)
            validate_reload(self.params, tree)
            tree = jax.tree.map(jnp.asarray, tree)
            # runtime-owned buffers before the swap: msgpack/orbax restores
            # and device_put can hand back zero-copy host views, and a
            # donating consumer of such a buffer corrupts the heap on this
            # image's jax (see jax_compat.ensure_donatable). Under a TP
            # mesh the caller's loader must pre-shard (shard_for_inference)
            # exactly as serve.py does at startup.
            from zero_transformer_tpu.utils.jax_compat import ensure_donatable

            tree = ensure_donatable(tree)
        except ReloadError as exc:
            self.stats["reloads_rejected"] += 1
            self._event("reload_rejected", error=str(exc))
            raise
        except Exception as exc:
            self.stats["reloads_rejected"] += 1
            self._event("reload_rejected", error=repr(exc))
            raise ReloadError(f"reload failed to load: {exc!r}") from exc
        swap_event = threading.Event()
        with self._lock:
            if self._dead is not None:
                # no tick thread will ever swap this in — fail fast (409)
                # instead of letting the admin caller block a full swap
                # timeout for a misleading "staged"
                self.stats["reloads_rejected"] += 1
                raise ReloadError(f"engine is not serving: {self._dead}")
            # a superseded (staged-but-unswapped) predecessor never serves:
            # its event stays unset and its caller truthfully gets "staged,
            # not swapped" rather than credit for a swap that was B's
            self._pending_params = (tree, swap_event)
            self._last_reload_event = swap_event
        return {
            "staged": True,
            "swapped": swap_event,  # PER-RELOAD: set only when THIS tree serves
            "reloads": self.stats["reloads"],
        }

    def _swap_pending_params(self) -> None:
        """Tick-thread side of reload: flip the param reference at a tick
        boundary, so prefill and the fused step inside one tick always see
        ONE tree. Active slots keep their cache rows — nothing retires."""
        with self._lock:
            pending, self._pending_params = self._pending_params, None
        if pending is None:
            return
        self.params, swap_event = pending
        self.stats["reloads"] += 1
        if self._prefix_cache is not None:
            # invalidation-on-reload: cached K/V spans embody the OLD
            # weights — serving them under the new tree would garble every
            # shared-prefix request. Flushed at the same tick boundary the
            # params flip, so no tick ever mixes the two.
            flushed = self._prefix_cache.flush()
            if flushed:
                self._event("prefix_cache_flushed", entries=flushed)
        # slots MID-chunked-prefill restart from token zero: their rows
        # hold old-weight K/V for [0, fill), and finishing the prompt under
        # the new tree would (a) decode from weight-mixed prompt K/V and
        # (b) bank those mixed spans into the just-flushed prefix cache,
        # poisoning every later shared-prefix request. Re-prefilling a few
        # chunks on a rare admin event is cheap; the request then matches
        # generate() under the NEW weights exactly. (Decoding slots keep
        # the PR 3 contract: they continue on the new weights from their
        # next token, nothing retires.)
        for slot, job in self._prefilling.items():
            job.fill = 0
            job.handle.prefix_hit_tokens = 0
            if self.kv_layout == "paged":
                # the slot may map SHARED pages from its pre-reload prefix
                # hit; re-prefilling under the new weights must not write
                # into pages other slots still read — drop every page and
                # refill fresh (the full worst case re-reserves)
                self.slots.reset_slot_pages(slot)
                self.slots.reserve(
                    slot, self._total_need_tokens(job.handle.request)
                )
        swap_event.set()
        self._event("reload_swapped", reloads=self.stats["reloads"])

    def wait_reload(self, timeout: Optional[float] = 10.0) -> bool:
        """Block until the most recently STAGED reload has swapped in."""
        event = self._last_reload_event
        return event.wait(timeout=timeout) if event is not None else False

    # ------------------------------------------------------------- scheduler

    def run(self, stop: threading.Event, idle_sleep: float = 0.001) -> None:
        """Scheduler loop for a background thread: step until ``stop`` or a
        completed drain.

        A non-tick exception (tick faults are supervised inside ``step``)
        would otherwise kill the thread SILENTLY: every in-flight handle
        waits forever on a 'done' event that never comes while /healthz
        keeps answering — a hung total outage. Fail loudly instead: finish
        every active and queued handle as ``failed`` (so blocked clients
        unblock with the error), then re-raise."""
        self.lifecycle.to(READY, reason="scheduler started")
        while not stop.is_set():
            try:
                busy = self.step()
            except Exception as exc:
                self._abort(f"scheduler died: {exc!r}")
                raise
            if self.draining and self.poll_drain():
                return  # drained clean: nothing queued or active remains
            if not busy:
                time.sleep(idle_sleep)
        # graceful stop: anything still queued or mid-decode will never get
        # another tick — finish it as failed so blocked consumers unblock
        self._abort("engine stopped")

    def _abort(self, reason: str) -> None:
        """Terminate every outstanding request with ``failed`` and mark the
        engine dead so later ``submit()`` calls fail fast too."""
        now = self.now()
        self.lifecycle.to(STOPPED, reason=reason)
        with self._lock:
            self._dead = reason
            queued = list(self._queue)
            self._queue.clear()
        for handle in queued:
            handle._finish(FAILED, now, error=reason)
        for slot, act in enumerate(self._active):
            if act is not None:
                act.handle._finish(FAILED, now, error=reason)
                self._active[slot] = None
        for slot in sorted(self._prefilling):
            self._prefilling.pop(slot).handle._finish(FAILED, now, error=reason)
        with self._lock:
            migrating = list(self._migrating.values())
            self._migrating.clear()
            pending, self._pending_imports = (
                list(self._pending_imports), deque()
            )
        for handle in migrating:
            handle._finish(FAILED, now, error=reason, retryable=True)
        for handle, _ in pending:
            handle._finish(FAILED, now, error=reason, retryable=True)
        self._profiler.abort()
        if "drained" not in reason:
            # a drain already dumped through _finish_drain; every OTHER path
            # here is an outage worth a post-mortem window
            self.flight.dump("abort", extra={"reason": reason})
            self.export_trace()

    def run_until_idle(self, max_ticks: int = 100_000) -> None:
        """Drive the scheduler synchronously until queue and slots drain
        (test / batch harness; raises if it fails to converge)."""
        for _ in range(max_ticks):
            if not self.step() and self.queue_depth == 0:
                return
        raise RuntimeError(f"engine not idle after {max_ticks} ticks")

    # --------------------------------------------------------------- metrics

    def metrics_snapshot(self) -> Dict[str, float]:
        """Aggregate serving metrics (milliseconds for latencies)."""
        elapsed = max(self.now() - self._started, 1e-9)
        snap: Dict[str, float] = {
            "tokens_per_sec": self.stats["tokens_out"] / elapsed,
            "slot_occupancy": self.active_count,
            "queue_depth": len(self._queue),
            "state": self.lifecycle.state,
            "uptime_s": self.lifecycle.uptime_s,
            "breaker_open": self._breaker.open,
            "itl_ewma_ms": (self._itl_ewma.value or 0.0) * 1e3,
            # prefill-path visibility: the chunk budget in force, how many
            # slots are mid-prefill, and the compiled one-shot bucket count
            # (the compile-storm gauge the bucket cap bounds)
            "prefill_chunk": self.prefill_chunk,
            "prefilling": len(self._prefilling),
            "prefill_buckets": len(self._buckets_seen),
            # paged-KV + speculation gauges (zeros when the feature is off,
            # so dashboards and the bench schema stay layout-agnostic)
            "kv_layout": self.kv_layout,
            "draft_k": self.draft_k,
            "page_pool_util": (
                self.slots.page_pool_util if self.kv_layout == "paged" else 0.0
            ),
            "page_pool_peak": (
                self.slots.pool.peak_in_use if self.kv_layout == "paged" else 0
            ),
            "cow_copies": (
                self.slots.cow_copies if self.kv_layout == "paged" else 0
            ),
            "acceptance_rate": (
                self.stats["accepted_tokens"] / self.stats["draft_tokens"]
                if self.stats["draft_tokens"]
                else 0.0
            ),
            # kernel-lane gauges (PR 11): is the paged-attention kernel
            # compiled into the decode program (vs the gather-to-slab
            # fallback), and is the sampling tail fused (vs the A/B
            # control's split dispatches)?
            "kernel_paged_attention": int(self._paged_kernel),
            "fused_tail": int(self.fused_tail),
            # disaggregation / migration gauges
            "role": self.role,
            "free_pages": self.free_pages,
            "migrations_in_flight": self._migrations_in_flight,
            "pending_imports": len(self._pending_imports),
        }
        # compile-family sanitizer gauges: distinct jit signatures seen per
        # labeled dispatch site vs its declared bound; a nonzero violation
        # count is the "serving got slow" compile-storm smoking gun
        for site in (self._ds_decode, self._ds_prefill, self._ds_spec,
                     self._ds_sample, self._ds_paged):
            short = site.name.rsplit(".", 1)[-1]
            snap[f"dispatch_{short}_signatures"] = site.distinct
            snap[f"dispatch_{short}_violations"] = site.violations
        if self._prefix_cache is not None:
            snap.update(self._prefix_cache.stats())
        else:
            snap.update({
                "prefix_hits": 0, "prefix_misses": 0, "prefix_stores": 0,
                "prefix_evictions": 0, "prefix_entries": 0,
                "prefix_hit_rate": 0.0,
            })
        # percentiles straight from the fixed-bucket histograms: O(buckets)
        # per quantile, no sample-list copy, no scheduler lock (the pre-PR7
        # deque sort under self._lock was the known scrape cost here)
        for name, hist in (
            ("ttft_ms", self._h_ttft),
            ("itl_ms", self._h_itl),
            ("itl_decode_ms", self._h_itl_decode),
        ):
            for q in (50, 90, 99):
                snap[f"{name}_p{q}"] = hist.quantile(q / 100.0) * 1e3
        for k in (
            "submitted", "completed", "rejected_queue_full", "rejected_invalid",
            "expired_queued", "expired_decoding", "cancelled", "tokens_out",
            "peak_occupancy", "peak_queue_depth",
            "tick_faults", "poisoned_slots", "breaker_trips", "shed_infeasible",
            "rejected_draining", "drain_forced", "reloads", "reloads_rejected",
            "prefill_chunks", "prefill_faults", "prefill_bucket_capped",
            "expired_prefilling",
            "page_faults", "pages_reclaimed", "preemptions",
            "spec_ticks", "draft_tokens", "accepted_tokens",
            "migrations_out", "migrations_in", "migration_failures",
            "prefill_handoffs", "import_replayed_tokens",
            "rejected_quota", "rejected_brownout", "shed_lower_class",
            "preempted_for_class", "brownout_transitions", "stalled_streams",
        ):
            snap[k] = self.stats[k]
        snap["brownout_rung"] = self._brownout_rung
        snap["queue_by_class"] = self._queue.counts()
        return snap

    def prometheus_text(self) -> str:
        """Prometheus text exposition (``text/plain; version=0.0.4``) of the
        registry: histograms directly, host counters/gauges through
        scrape-time callbacks — the tick thread never pays for exposition."""
        return self.registry.render()

    def _register_exports(self) -> None:
        """Wire the host-side ``stats`` counters and live gauges into the
        Prometheus registry as scrape-time callbacks (the hot path keeps
        its plain-int increments; only a scrape pays the read)."""
        reg = self.registry
        for key, help_text in (
            ("submitted", "Requests submitted (accepted + rejected)"),
            ("completed", "Requests finished with status done"),
            ("rejected_queue_full", "Admission rejections: queue full"),
            ("rejected_invalid", "Admission rejections: invalid request"),
            ("rejected_draining", "Admission rejections while draining"),
            ("shed_infeasible", "Deadline-infeasible sheds at admission"),
            ("expired_queued", "Deadline expiries while queued"),
            ("expired_prefilling", "Deadline expiries during prefill"),
            ("expired_decoding", "Deadline expiries mid-decode"),
            ("cancelled", "Client cancellations honored"),
            ("tokens_out", "Tokens emitted to clients"),
            ("tick_faults", "Supervised decode-tick faults"),
            ("poisoned_slots", "Slots retired by the non-finite guard"),
            ("breaker_trips", "Circuit-breaker trips (DEGRADED + rebuild)"),
            ("drain_forced", "Generations force-finished at drain deadline"),
            ("reloads", "Hot weight reloads swapped in"),
            ("reloads_rejected", "Hot weight reloads rejected"),
            ("prefill_chunks", "Chunk-prefill row dispatches"),
            ("prefill_faults", "Supervised chunk-prefill faults"),
            ("prefill_bucket_capped", "One-shot prefill bucket-cap events"),
            ("page_faults", "Page-pool exhaustions that reclaimed prefix pages"),
            ("pages_reclaimed", "Prefix-cache pages reclaimed under pressure"),
            ("preemptions", "Requests preempted for KV pages (last resort)"),
            ("spec_ticks", "Speculative decode ticks"),
            ("draft_tokens", "Draft tokens proposed"),
            ("accepted_tokens", "Draft tokens accepted by verify"),
            ("migrations_out", "Streams shipped to another replica"),
            ("migrations_in", "Migrated streams imported and continued"),
            ("migration_failures", "Ship failures (fell back to recompute)"),
            ("prefill_handoffs", "Disaggregated prefill-to-decode handoffs"),
            ("import_replayed_tokens",
             "Tokens recomputed by imported streams (0 by construction)"),
            ("rejected_quota", "Admission rejections: tenant quota exhausted"),
            ("rejected_brownout",
             "Admission rejections: brownout suspended the class"),
            ("shed_lower_class",
             "Queue-full sheds that evicted a lower QoS class"),
            ("preempted_for_class",
             "Running streams preempted for a waiting higher class"),
            ("brownout_transitions", "Brownout rung transitions"),
            ("stalled_streams",
             "Streams retired because the client stalled (emit overflow)"),
        ):
            reg.counter_func(
                f"serve_{key}", help_text,
                (lambda k=key: self.stats[k]),
            )
        reg.gauge_func(
            "serve_queue_depth", "Requests waiting for a slot",
            lambda: len(self._queue),
        )
        reg.gauge_func(
            "serve_brownout_rung",
            "Brownout rung index (0=normal .. 3=suspend_batch)",
            lambda: BROWNOUT_RUNGS.index(self._brownout_rung),
        )
        reg.gauge_func(
            "serve_slot_occupancy", "Slots actively decoding",
            lambda: self.active_count,
        )
        reg.gauge_func(
            "serve_prefilling_slots", "Slots mid-chunked-prefill",
            lambda: len(self._prefilling),
        )
        reg.gauge_func(
            "serve_slots", "Configured decode slots", lambda: self.n_slots
        )
        reg.gauge_func(
            "serve_breaker_open", "1 while the circuit breaker is open",
            lambda: 1 if self._breaker.open else 0,
        )
        reg.gauge_func(
            "serve_uptime_seconds", "Engine lifetime on its own clock",
            lambda: self.lifecycle.uptime_s,
        )
        reg.gauge_func(
            "serve_itl_ewma_seconds", "Shedding's measured ITL EWMA",
            lambda: self._itl_ewma.value or 0.0,
        )
        reg.gauge_func(
            "serve_prefill_buckets", "Compiled one-shot prefill buckets",
            lambda: len(self._buckets_seen),
        )
        reg.gauge_func(
            "serve_page_pool_util", "Paged-KV pool utilization (0 when slab)",
            lambda: (
                self.slots.page_pool_util if self.kv_layout == "paged" else 0.0
            ),
        )
        # page-pool pressure as first-class scrape families (pre-PR12 a
        # router could only see free_pages by polling /healthz)
        reg.gauge_func(
            "serve_free_pages",
            "Spare KV capacity (free pool pages, or free slots when slab)",
            lambda: self.free_pages,
        )
        reg.counter_func(
            "serve_cow_copies",
            "Copy-on-write page copies (shared page written post-import/share)",
            lambda: (
                self.slots.cow_copies if self.kv_layout == "paged" else 0
            ),
        )
        reg.gauge_func(
            "serve_migrations_in_flight",
            "Streams exported and awaiting their ship acknowledgement",
            lambda: self._migrations_in_flight,
        )
        reg.gauge_func(
            "serve_pending_imports",
            "Imported streams awaiting placement into a slot",
            lambda: len(self._pending_imports),
        )
        reg.gauge_func(
            "serve_prefix_cache_entries", "Prefix-cache entries resident",
            lambda: (
                len(self._prefix_cache) if self._prefix_cache is not None else 0
            ),
        )
        reg.gauge_func(
            "serve_trace_spans_dropped",
            "Spans pushed out of the bounded trace ring",
            lambda: self.tracer.dropped,
        )
        # the fleet-standard name (PR 15): same value on every process
        # (router, replicas, trainer exporter) so one dashboard query
        # covers trace-truncation honesty fleet-wide
        reg.gauge_func(
            "obs_spans_dropped",
            "Spans dropped by ring overflow (trace truncation honesty)",
            lambda: self.tracer.dropped,
        )
        # per-device HBM with max/mean rollups (None on backends without
        # memory stats — the callbacks then render no samples). One shared
        # short-TTL read per scrape: the three gauges render back to back,
        # and each hbm_device_stats() call is a memory_stats runtime query
        # PER DEVICE — tripling that per scrape is pure waste
        hbm_cache = {"t": -1.0, "v": None}

        def _hbm() -> dict:
            t = time.monotonic()
            if t - hbm_cache["t"] > 0.25:
                hbm_cache["v"] = hbm_device_stats()
                hbm_cache["t"] = t
            return hbm_cache["v"] or {}

        reg.gauge_func(
            "hbm_used_gigabytes", "Per-device HBM in use",
            lambda: [
                ({"device": str(i)}, gb)
                for i, gb in enumerate(_hbm().get("per_device_gb", []))
            ],
        )
        reg.gauge_func(
            "hbm_used_gigabytes_max", "Max HBM in use across local devices",
            lambda: _hbm().get("max_gb"),
        )
        reg.gauge_func(
            "hbm_used_gigabytes_mean", "Mean HBM in use across local devices",
            lambda: _hbm().get("mean_gb"),
        )

    # ------------------------------------------------------------- profiling

    def request_profile(self, ticks: int) -> Dict[str, Any]:
        """Stage a ``jax.profiler`` capture of the next ``ticks`` scheduler
        ticks (``POST /admin/profile`` lands here). Thread-safe staging;
        the tick thread alone starts/stops the trace. Raises RuntimeError
        while draining/stopped, without an ``obs_dir``, or when a capture
        is already in progress."""
        with self._lock:
            if self._dead is not None:
                raise RuntimeError(f"engine is not serving: {self._dead}")
            if self.lifecycle.state == DRAINING:
                raise RuntimeError(
                    "engine is draining; profile capture rejected"
                )
            info = self._profiler.request(
                ticks, name=f"serve_tick{self._tick}"
            )
        self._event("profile_requested", ticks=ticks, path=info["path"])
        return info

    @property
    def profile_active(self) -> bool:
        return self._profiler.active

    @property
    def profiles_completed(self) -> List[str]:
        return list(self._profiler.completed)

    def export_trace(self, path: Optional[str] = None) -> Optional[str]:
        """Write the span ring as Perfetto/Chrome-trace JSON (default:
        ``<obs_dir>/trace_serve.json``) plus an incremental append to
        ``<obs_dir>/spans.jsonl`` beside ``metrics.jsonl``."""
        if path is None:
            if self.obs_dir is None:
                return None
            path = str(Path(self.obs_dir) / "trace_serve.json")
        out = self.tracer.write_chrome_trace(path)
        if self.obs_dir is not None:
            self.tracer.write_jsonl(Path(self.obs_dir) / "spans.jsonl")
        return out
